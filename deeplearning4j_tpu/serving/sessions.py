"""Stateful decode serving: per-request sessions over a shared KV slot
pool, stepped through the continuous-batching scheduler.

The old decode path (`utils/textgen.generate`) drives `rnn_time_step`,
which mutates MODEL-GLOBAL carries — one autoregressive stream per net,
and a server would have to dedicate a model replica per conversation.
This module turns decode into data: each session owns a SLOT in a
`KVSlotPool` (one batch row of a [slots, ...] carry tree), and every
step — prefill chunk or fused decode window — is submitted to the
`ContinuousBatchingScheduler` as an ordinary one-row request against a
dedicated `<model>@decode` endpoint. The scheduler coalesces whatever
rows are queued (sessions at different phases — one mid-prefill,
another deep into decode — share the same dispatch), and the
endpoint's `run_batch` runs at most two jitted programs: one
`session_step` over the co-batched prefill chunks (its logits are
never read back), then one `session_decode_window` that advances every
decoding lane K TOKENS — sampling on-device (greedy/temperature/
top-k/top-p as lax ops), feeding each sample back through the model
inside a `lax.scan`, early-exiting lanes on EOS/budget via the active
mask. The callback chain consumes K sampled tokens per round-trip
instead of one: host round-trips, the dominant decode cost, are
amortized K-fold (`decode_loop_policy` picks K; DL4J_TPU_DECODE_LOOP /
DL4J_TPU_DECODE_K force it). Greedy fused output is bit-exact against
step-by-step decode by contract (tests/test_fused_decode.py).

Shapes are the contract: every dispatch runs at a prefill bucket (1 or
`prefill_chunk`) and/or the one window length K — all warmed at
construction, so session churn causes ZERO recompiles — the watchdog
stays quiet (see PERF_NOTES). TTFT/ITL histograms, token counters and
shared-dispatch counters ride the server's metrics registry so the
closed-loop bench can reconcile its client-side numbers; ITL inside a
window is amortized (window gap / tokens) since tokens arrive in
bursts of K.

Speculative decoding rides the same machinery: wire a DRAFT net in and
`spec_decode_policy` flips each window to draft-propose + target-verify
— the draft proposes spec_k tokens through its own fused window (its
slots live in a lockstep KVSlotPool, registered as `<model>@draft`),
the target scores all of them in ONE chunked forward, and accept/
reject (utils/sampling.spec_accept_lanes: greedy longest-prefix fast
path, standard rejection rule otherwise) stays on device. Rejected
proposals are un-written by rewinding per-slot positions, so both nets
must be rewind-capable (no recurrent carries, no rolling rings). The
host still pays exactly one sync per window — the verify's packed
result rows. `kv_dtype_policy` independently picks the pools' cache
storage (int8/fp8 with per-(token, kv-head) scales), multiplying
slots-per-chip at fixed memory.

Prefix cache: when `prefix_cache_policy` verdicts "paged", the pool
stores KV in fixed-size pages behind per-slot page tables and a radix
index (`prefix_cache.py`) maps prompt prefixes to refcounted shared
page chains. Admission matches the prompt stem, adopts the matched
pages, forks at most one partially-matched page (copy-on-write) and
installs the slot's table — all under the pool lock, all traced-scalar
programs — then prefill RESUMES after the cached prefix: a warm prefix
never re-prefills, so its TTFT approaches one decode window. Completed
prefills are offered back to the index at the phase-0→1 transition.
Eviction is leaf-first LRU over refcount-1 (cache-only) pages; a live
session's pages can never be reclaimed. Mutually exclusive with the
draft model (a draft's lockstep pool must prefill every token).

Hot-swap: the manager subscribes to registry deploy hooks for its base
model. In the "warm" phase it verifies the candidate can host the live
carry tree and pre-compiles its session-step buckets (raising rides
the normal rollback — sessions keep serving the old version); in the
"flipped" phase it rebinds the pool, migrating every live session onto
the new weights mid-stream instead of dropping them.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.observe import reqtrace
from deeplearning4j_tpu.ops.kernel_defaults import (
    decode_loop_policy, kv_dtype_policy, prefix_cache_policy,
    spec_decode_policy,
)
from deeplearning4j_tpu.serving.kv_pool import (
    IncompatibleSessionSwapError, KVSlotPool, SlotPoolExhaustedError,
)
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.registry import ModelEntry
from deeplearning4j_tpu.serving.scheduler import (
    DeadlineExceededError, RequestShedError, SchedulerClosedError,
)
from deeplearning4j_tpu.utils.sampling import (
    SamplingParams, lane_param_arrays,
)
from deeplearning4j_tpu.utils.textgen import (
    _encode, _input_encoding, _resolve_net,
)

logger = logging.getLogger("deeplearning4j_tpu")

_OUTCOMES = ("completed", "cancelled", "expired", "failed")


class DecodeSession:
    """One streaming generation: a slot, a cursor into the prompt, the
    sampling state, and a queue of token events the client drains."""

    def __init__(self, sid: str, slot: int, prompt: np.ndarray, *,
                 max_tokens: int, params: SamplingParams,
                 seed: Optional[int], deadline_ms: Optional[float],
                 eos_id: Optional[int], trace=None):
        self.id = sid
        self.slot = slot
        self.prompt = prompt
        # sampled requests carry their TraceContext through every
        # resubmitted step; None on the sampled-off fast path
        self.trace = trace
        self.max_tokens = int(max_tokens)
        self.params = params
        # sampling runs ON-DEVICE inside the fused window: the session
        # carries a threefry base key, and token i always draws with
        # fold_in(base_key, i) — the stream is deterministic in the seed
        # and invariant to K and to dispatch co-batching
        seed = 0 if seed is None else int(seed)
        self.base_key = np.array(
            [(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF], np.uint32)
        self.eos_id = eos_id
        self.opened_at = time.monotonic()
        self.deadline = (None if deadline_ms is None
                         else self.opened_at + deadline_ms / 1000.0)
        self.generated: List[int] = []
        self.outcome: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.ttft_ms: Optional[float] = None
        self.done = threading.Event()
        self.cancelled = False
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._off = 0              # prompt tokens already submitted
        self._last_tok_at: Optional[float] = None
        self._finished = False     # guarded by the manager lock
        # speculative-decode bookkeeping (manager-owned; safe to read and
        # write in run_batch because each session has exactly one row in
        # flight): how far the draft's positions must rewind on window
        # entry, and the catch-up token (d_k) the draft never cached
        # when the previous window fully accepted
        self._spec_rewind = 0
        self._spec_pre_tok = 0
        self._spec_pre_valid = False
        # paged prefix-cache bookkeeping (manager-owned): the session's
        # physical page chain, how many prompt tokens admission found
        # already cached (prefill skips them), and whether the finished
        # prefill was offered to the radix index yet
        self._pages: List[int] = []
        self._cached_len = 0
        self._prefix_inserted = False
        # the manager's radix deploy generation at admission: when a
        # hot-swap flips mid-stream, this session's KV belongs to the
        # old weights and must not be offered back to the radix index
        self._gen = 0
        # fleet disaggregation: a prefill-only session runs the prompt
        # stem through prefill, offers the pages to the radix index, and
        # finishes WITHOUT sampling — the decode role lives on another
        # replica, which imports the pages and decodes from the warm stem
        self._prefill_only = False

    # -------------------------------------------------------- client API
    def stream(self, timeout: Optional[float] = None):
        """Yield token events as they arrive: `{"token", "index"}` per
        token, then exactly one terminal event (`{"done": ...}` or
        `{"error": ...}`). Raises queue.Empty if `timeout` seconds pass
        without an event (a stalled-stream guard for clients)."""
        while True:
            ev = self._events.get(timeout=timeout)
            yield ev
            if "done" in ev or "error" in ev:
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the session finishes; returns the generated token
        ids, or raises the session's error (deadline, shed, crash)."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"session {self.id} still running")
        if self.error is not None:
            raise self.error
        return list(self.generated)

    def cancel(self) -> None:
        """Request cancellation; honored at the next window boundary
        (there is always at most one row in flight per session, and a
        window is at most `fused_k` tokens). Tokens already streamed
        stay streamed."""
        self.cancelled = True

    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - time.monotonic()) * 1000.0

    def describe(self) -> dict:
        return {"id": self.id, "slot": self.slot,
                "prompt_len": int(self.prompt.size),
                "generated": len(self.generated),
                "max_tokens": self.max_tokens,
                "ttft_ms": self.ttft_ms,
                "outcome": self.outcome,
                "trace_id": (self.trace.trace_id
                             if self.trace is not None else None)}


class DecodeSessionManager:
    """Owns the slot pool, the `<model>@decode` endpoint, and the
    callback chain that steps every live session."""

    def __init__(self, registry, scheduler, model: str = "default", *,
                 slots: int = 4, prefill_chunk: int = 8,
                 fused_k: Optional[int] = None,
                 draft_net=None, spec_k: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 page_len: Optional[int] = None,
                 metrics=None, warm: bool = True):
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        base = registry.get(model)      # KeyError if not deployed
        if not hasattr(base.net, "session_carries"):
            raise TypeError(
                f"decode sessions need a net with session_carries() "
                f"(MultiLayerNetwork); got {type(base.net).__name__}")
        self.registry = registry
        self.scheduler = scheduler
        self.model = model
        self.decode_name = f"{model}@decode"
        self.prefill_chunk = int(prefill_chunk)
        self.buckets = sorted({1, self.prefill_chunk})
        # decode-loop verdict: how many tokens one dispatch advances.
        # K is part of the compile key, so it is fixed per manager (and
        # bucketed inside the policy) — request churn never mints a new
        # program. "stepwise" is simply K=1 through the same window
        # program: one code path, on-device sampling everywhere.
        loop = decode_loop_policy(
            k=fused_k,
            capable=hasattr(base.net, "session_decode_window"))
        if loop.kind == "stepwise" and \
                not hasattr(base.net, "session_decode_window"):
            raise TypeError(
                f"decode sessions need session_decode_window "
                f"(MultiLayerNetwork); got {type(base.net).__name__}")
        self.loop_kind = loop.kind
        self.fused_k = int(loop.k)
        self._loop_reason = loop.reason
        self._lock = threading.Lock()
        self._net = base.net
        self._sessions: Dict[str, DecodeSession] = {}
        self._sid = itertools.count(1)
        self._seed_rng = np.random.default_rng()
        self._closed = False

        first, vocab = _resolve_net(base.net)
        self.vocab = int(vocab)
        self._encoding = _input_encoding(first)
        self._limit = base.net.decode_limit()

        # kv-dtype verdict: storage dtype for every pool this manager
        # owns — target and draft slots quantize together, mixed-dtype
        # pools would double the compiled-program set for no benefit
        kvp = kv_dtype_policy(kv_dtype)
        self.kv_dtype = kvp.kind
        self._kv_reason = kvp.reason

        # speculative-decode verdict: needs a draft that exists, shares
        # the target's vocabulary (acceptance compares the two nets'
        # distributions token for token) and can REWIND — as must the
        # target, since rejected proposals are un-written by snapping
        # per-slot positions back (recurrent carries and rolling rings
        # hold state that cannot be un-written, so either disqualifies)
        self.draft_net = draft_net
        spec_capable = False
        if draft_net is not None and \
                hasattr(draft_net, "session_propose_window"):
            _, dv = _resolve_net(draft_net)
            spec_capable = (
                int(dv) == self.vocab
                and getattr(base.net, "spec_decode_capable",
                            lambda: False)()
                and draft_net.spec_decode_capable())
        spec = spec_decode_policy(spec_k, capable=spec_capable)
        self.spec_enabled = spec.kind == "spec"
        self.spec_k = int(spec.k)
        self._spec_reason = spec.reason
        self.draft_name = f"{model}@draft" if self.spec_enabled else None

        # prefix-cache verdict: paged KV + radix prefix reuse. Needs a
        # net whose attention caches can be paged (non-rolling, uniform
        # max_cache — prefix_cache_capable) and NO active draft: the
        # draft's lockstep pool prefills every prompt token into its own
        # cache, so skipping the target's prefill would desync the pair
        mc = None
        for layer in getattr(base.net, "layers", ()):
            if hasattr(layer, "decode_carry") and \
                    hasattr(layer, "max_cache"):
                mc = int(layer.max_cache)
                break
        pcap = (mc is not None
                and getattr(base.net, "prefix_cache_capable",
                            lambda: False)()
                and not self.spec_enabled)
        ppol = prefix_cache_policy(page_len, max_cache=mc, capable=pcap)
        self.prefix_enabled = ppol.kind == "paged"
        self.page_len = int(ppol.page_len)
        self._prefix_reason = ppol.reason

        from deeplearning4j_tpu.observe import get_registry
        if metrics is None:
            metrics = get_registry()
        self.metrics = metrics
        # the policy consults above counted on the process-global
        # registry (record_dispatch); mirror onto the server's registry
        # when it is a private one so /metrics surfaces the decode_loop,
        # spec_decode and kv_dtype verdicts too
        if metrics is not get_registry():
            metrics.counter("kernel_dispatch_total", op="decode_loop",
                            impl=self.loop_kind).inc()
            metrics.counter("kernel_dispatch_total", op="spec_decode",
                            impl="spec" if self.spec_enabled
                            else "plain").inc()
            metrics.counter("kernel_dispatch_total", op="kv_dtype",
                            impl=self.kv_dtype).inc()
            metrics.counter("kernel_dispatch_total", op="prefix_cache",
                            impl="paged" if self.prefix_enabled
                            else "off").inc()
        self.pool = KVSlotPool(
            base.net, slots, model=model, metrics=metrics,
            kv_dtype=self.kv_dtype,
            page_len=self.page_len if self.prefix_enabled else None)
        self.prefix_cache = (PrefixCache(self.pool, metrics=metrics)
                             if self.prefix_enabled else None)
        # radix deploy generation (guarded by the pool lock): bumped at
        # every hot-swap flip alongside flush(). A session stamped with
        # an older generation prefilled under the OLD weights — its KV
        # must never be re-indexed after the flip, or new sessions would
        # match stale-weight pages and decode wrong logits silently.
        self._prefix_gen = 0
        # the draft rides a lockstep slot pool: slot i of the draft pool
        # always belongs to the session holding slot i of the target
        # pool, so no independent alloc/free bookkeeping — _finish just
        # zeroes the row for the next tenant
        self.draft_pool = None
        if self.spec_enabled:
            self.draft_pool = KVSlotPool(
                draft_net, slots, model=self.draft_name,
                metrics=metrics, kv_dtype=self.kv_dtype)
        self._g_active = metrics.gauge("serving_sessions_active",
                                       model=model)
        self._c_opened = metrics.counter("serving_sessions_total",
                                         model=model, outcome="opened")
        self._c_out = {o: metrics.counter("serving_sessions_total",
                                          model=model, outcome=o)
                       for o in _OUTCOMES}
        self._c_tokens = metrics.counter("serving_decode_tokens_total",
                                         model=model)
        self._h_ttft = metrics.histogram("serving_ttft_ms", model=model)
        self._h_itl = metrics.histogram("serving_itl_ms", model=model)
        self._c_disp = metrics.counter("serving_decode_dispatches_total",
                                       model=model)
        self._c_rows = metrics.counter(
            "serving_decode_dispatch_rows_total", model=model)
        self._c_shared = metrics.counter(
            "serving_decode_shared_dispatches_total", model=model)
        # fused-window accounting: windows run and tokens they emitted —
        # dispatches/tokens is the round-trips-per-token the bench trends
        self._c_windows = metrics.counter(
            "serving_decode_windows_total", model=model)
        self._c_window_tokens = metrics.counter(
            "serving_decode_window_tokens_total", model=model)
        # spec accounting: the counter PAIR makes the acceptance rate
        # derivable from /metrics alone (accepted / draft), and the
        # per-lane-window histogram gives its distribution
        self._c_draft_toks = metrics.counter("draft_tokens_total",
                                             model=model)
        self._c_accepted = metrics.counter("accepted_tokens_total",
                                           model=model)
        self._h_accept = metrics.histogram(
            "serving_spec_acceptance_rate", model=model)
        # commsmon reshard witness — None when DL4J_TPU_COMMSMON is off,
        # so the disabled dispatch path pays one attribute read
        from deeplearning4j_tpu.observe.commsmon import get_reshard_witness
        self._reshard = get_reshard_witness()

        # the decode endpoint: an ordinary registry entry whose "runner"
        # is this manager — scheduler dispatch, drain-on-retire and
        # registry.close() all work unchanged
        self.entry = registry.register_entry(
            self.decode_name,
            ModelEntry(self.decode_name, getattr(base, "version", None),
                       base.net, runner=self))
        # the draft is a first-class registry citizen (PR 7 seam): it
        # shows up in describe(), and registry.close() reaches this
        # manager through its runner (shutdown is idempotent)
        if self.spec_enabled:
            registry.register_entry(
                self.draft_name,
                ModelEntry(self.draft_name, getattr(base, "version", None),
                           draft_net, runner=self))
        registry.add_deploy_hook(model, self._deploy_hook)
        # kernel-policy verdict cached once (and refreshed on hot-swap):
        # session-step spans stamp it per ITL step, and re-deriving it
        # per dispatch would price policy evaluation into the hot path
        self._policy_kind = self._policy_brief()
        if warm:
            self.warmup()

    # ------------------------------------------------------------ warmup
    def _feat_dim(self) -> int:
        return 1 if self._encoding == "ids" else self.vocab

    def _session_carries(self, net):
        """Build a carry tree shaped exactly like the pool's (paged
        geometry included) — warmup and swap-compat checks must compile
        and compare the same programs the live tree will run."""
        if self.prefix_enabled:
            return net.session_carries(self.pool.slots,
                                       kv_dtype=self.kv_dtype,
                                       page_len=self.pool.page_len,
                                       pages=self.pool.pages)
        return net.session_carries(self.pool.slots,
                                   kv_dtype=self.kv_dtype)

    def _compile_buckets(self, net) -> None:
        """Run one all-lanes-inactive step per prefill bucket plus one
        all-lanes-inactive window program (plain fused window, or the
        propose+verify pair when speculating) so every dispatch shape
        this manager will ever use is compiled before traffic (the
        zero-recompiles-after-warmup contract the bench asserts). On a
        hot-swap warm phase `net` is the TARGET candidate; the draft is
        not part of the deploy, so its already-compiled programs feed
        the candidate's verify warmup."""
        carries = self._session_carries(net)
        S, F = self.pool.slots, self._feat_dim()
        act = np.zeros((S,), bool)
        knobs = dict(temperature=np.ones((S,), np.float32),
                     top_k=np.full((S,), self.vocab, np.int32),
                     top_p=np.ones((S,), np.float32),
                     greedy=np.ones((S,), bool),
                     keys=np.zeros((S, 2), np.uint32),
                     offsets=np.zeros((S,), np.int32))
        for b in self.buckets:
            x = np.zeros((S, b, F), np.float32)
            val = np.zeros((S, b), np.float32)
            out, _ = net.session_step(x, carries, active=act, valid=val)
            # materialize: compile time must land in warmup, not on the
            # first live dispatch
            # graft: allow-sync(warmup barrier — pre-traffic by design)
            np.asarray(out)
        if self.spec_enabled:
            # graft: allow(GL701): warmup runs at construction/deploy
            # time, before the draft pool is shared with request
            # threads; steady-state readers take the pool lock
            draft = self.draft_pool.net
            dcar = draft.session_carries(S, kv_dtype=self.kv_dtype)
            for b in self.buckets:
                x = np.zeros((S, b, F), np.float32)
                val = np.zeros((S, b), np.float32)
                out, _ = draft.session_step(x, dcar, active=act,
                                            valid=val)
                # graft: allow-sync(warmup barrier — pre-traffic)
                np.asarray(out)
            d_toks, d_probs, _ = draft.session_propose_window(
                np.zeros((S,), np.int64), dcar, active=act,
                k=self.spec_k, rewind=np.zeros((S,), np.int32),
                pre_tokens=np.zeros((S,), np.int32),
                pre_valid=np.zeros((S,), bool), **knobs)
            packed, _ = net.session_verify_window(
                np.zeros((S,), np.int64), carries, active=act,
                k=self.spec_k, draft_tokens=d_toks, draft_probs=d_probs,
                budgets=np.zeros((S,), np.int32),
                eos_ids=np.full((S,), -1, np.int32), **knobs)
            # graft: allow-sync(warmup barrier — pre-traffic by design)
            np.asarray(packed)
        else:
            toks, _, _ = net.session_decode_window(
                np.zeros((S,), np.int64), carries, active=act,
                k=self.fused_k, budgets=np.zeros((S,), np.int32),
                eos_ids=np.full((S,), -1, np.int32), **knobs)
            # graft: allow-sync(warmup barrier — pre-traffic by design)
            np.asarray(toks)

    def warmup(self) -> None:
        # graft: allow(GL701): warmup runs at construction/deploy time,
        # before the pool is shared with request threads; steady-state
        # readers take the pool lock in run_batch
        self._compile_buckets(self.pool.net)

    # ---------------------------------------------------------- sessions
    def open_session(self, prompt_ids, *, max_tokens: int = 16,
                     temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     greedy: bool = False, seed: Optional[int] = None,
                     deadline_ms: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     alloc_timeout_s: float = 0.0,
                     trace=None,
                     prefill_only: bool = False) -> DecodeSession:
        """Admit one generation: claim a slot (SlotPoolExhaustedError →
        503 upstream), validate the token budget against the net's
        decode limit, and kick off the prefill→decode callback chain.
        Returns immediately; consume via `stream()`/`result()`."""
        prompt = np.asarray(prompt_ids, dtype=np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt_ids must contain at least one token")
        if prompt.min() < 0 or prompt.max() >= self.vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {self.vocab})")
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        params = SamplingParams(temperature=temperature, top_k=top_k,
                                top_p=top_p, greedy=greedy)
        # a speculative verify transiently writes spec_k + 1 entries
        # past the confirmed position before the cut snaps it back; the
        # cache must leave that headroom or the last window's scatter
        # would silently drop rows
        head = (self.spec_k + 1) if self.spec_enabled else 0
        if self._limit is not None and \
                int(prompt.size) + int(max_tokens) + head > self._limit:
            raise ValueError(
                f"prompt ({prompt.size}) + max_tokens ({max_tokens})"
                f"{f' + spec headroom ({head})' if head else ''} "
                f"exceeds the decode budget of {self._limit} for this "
                f"net (non-rolling cache)")
        with self._lock:
            if self._closed:
                raise SchedulerClosedError("session manager is shut down")
            if seed is None:
                # unseeded requests still get independent device streams
                seed = int(self._seed_rng.integers(0, 2 ** 63))
        slot = self.pool.alloc(alloc_timeout_s)
        cached_len, pages, gen = 0, [], 0
        if self.prefix_enabled:
            try:
                with self.pool.lock():
                    # graft: allow(GL301): guarded by the pool lock just
                    # above — _prefix_gen shares the pool's Condition
                    gen = self._prefix_gen
                    cached_len, pages = self._admit_pages(
                        slot, prompt, int(max_tokens), head)
            except BaseException:
                self.pool.free(slot)
                raise
        sess = DecodeSession(
            f"s{next(self._sid):06d}", slot, prompt,
            max_tokens=max_tokens, params=params, seed=seed,
            deadline_ms=deadline_ms, eos_id=eos_id, trace=trace)
        sess._pages = pages
        sess._cached_len = cached_len
        sess._gen = gen
        sess._prefill_only = bool(prefill_only)
        # prefill resumes AFTER the cached prefix: a fully warm stem
        # goes straight to the decode window (TTFT ~ one window)
        sess._off = cached_len
        with self._lock:
            self._sessions[sess.id] = sess
            n_active = len(self._sessions)
        self._c_opened.inc()
        self._g_active.set(n_active)
        try:
            from deeplearning4j_tpu.observe import get_flight
            get_flight().record("session_open", model=self.model,
                                session=sess.id, slot=slot,
                                prompt_len=int(prompt.size),
                                max_tokens=int(max_tokens))
        # graft: allow(GL403): breadcrumbs are best-effort
        except Exception:
            pass
        self._submit_next(sess)
        return sess

    def open_prefill(self, prompt_ids, *,
                     deadline_ms: Optional[float] = None,
                     alloc_timeout_s: float = 0.0,
                     trace=None) -> DecodeSession:
        """Admit a prefill-ONLY session (fleet prefill role): run the
        prompt stem through chunked prefill, offer the resulting pages
        to the radix index, and finish with zero generated tokens. The
        warm stem is then exportable via the fleet handoff path. Needs
        the prefix cache — without an index the prefilled pages would
        be unreachable the moment the slot frees."""
        if not self.prefix_enabled:
            raise ValueError(
                "prefill-only sessions require a paged pool with the "
                "prefix cache enabled (page_len=...)")
        return self.open_session(
            prompt_ids, max_tokens=1, greedy=True,
            deadline_ms=deadline_ms, alloc_timeout_s=alloc_timeout_s,
            trace=trace, prefill_only=True)

    def get_session(self, sid: str) -> Optional[DecodeSession]:
        with self._lock:
            return self._sessions.get(sid)

    def cancel(self, sid: str) -> bool:
        sess = self.get_session(sid)
        if sess is None:
            return False
        sess.cancel()
        return True

    # ----------------------------------------------- paged admission
    def _admit_pages(self, slot: int, prompt: np.ndarray,
                     max_tokens: int, head: int):
        """All page bookkeeping for one session happens HERE, under the
        pool lock, at admission: match the prompt stem against the radix
        index, adopt the shared full pages by reference, fork (copy) at
        most ONE partially-matched page, allocate fresh pages for the
        rest of the token budget, and install the slot's page table +
        position in one jitted program. Steady-state windows then never
        touch host page state — page indices are traced scalars inside
        the compiled step, zero extra syncs and zero recompiles. Returns
        `(cached_len, page_chain)`. Caller holds the pool lock."""
        Lp = self.pool.page_len
        stem = int(prompt.size) - 1
        cl, shared, partial = self.prefix_cache.match(prompt[:stem])
        # pin every matched page BEFORE the eviction pass below can run:
        # match() leaves a cache-only chain at refcount 1, which the LRU
        # sweep would be free to reclaim out from under this very
        # admission. Refcount 2 (cache + us) makes the matched pages
        # unevictable by construction. The shared-page pins double as
        # the session's own references; the partial source's pin is
        # transient — it only has to survive until the CoW copy.
        pinned = list(shared)
        if partial is not None:
            pinned.append(partial[0])
        for p in pinned:
            self.pool.page_ref_locked(p)
        fresh = []
        try:
            total = int(prompt.size) + max_tokens + head
            need = -(-total // Lp)      # ceil: whole session footprint
            n_fresh = need - len(shared)
            short = n_fresh - self.pool.pages_free_locked()
            if short > 0:
                # LRU-evict cold cache-only chains; live (and pinned)
                # pages untouchable
                self.prefix_cache.evict(short)
            if self.pool.pages_free_locked() < n_fresh:
                raise SlotPoolExhaustedError(
                    f"need {n_fresh} KV pages, "
                    f"{self.pool.pages_free_locked()} free after "
                    f"eviction")
            fresh = self.pool.page_alloc_locked(n_fresh)
            chain = list(shared) + fresh
            if partial is not None:
                # the one copy-on-write fork of an admission: the match
                # ends mid-page, so the follower takes a private copy
                # and prefill resumes inside it at the divergence offset
                src, _ = partial
                self.pool.copy_page_locked(src, chain[len(shared)])
                self.prefix_cache.note_cow_fork()
            self.pool.install_pages_locked(slot, chain, cl)
        except BaseException:
            # no page escapes a failed admission: drop the fresh pages
            # and every pin taken above
            for p in fresh:
                self.pool.page_unref_locked(p)
            for p in pinned:
                self.pool.page_unref_locked(p)
            raise
        if partial is not None:
            # copy done — the partial source goes back to cache-only
            # (the session keeps the private copy, not the source)
            self.pool.page_unref_locked(partial[0])
        return cl, chain

    def _insert_prefix(self, sess: DecodeSession) -> None:
        """Offer a freshly completed prefill to the radix index (called
        once, at the session's phase-0 -> phase-1 transition, when every
        prefill future has resolved). Best-effort: indexing is a perf
        optimization and must never take down the session chain."""
        stem = sess.prompt.size - 1
        if stem <= 0 or not sess._pages:
            return
        try:
            with self.pool.lock():
                if sess._gen != self._prefix_gen:
                    # a hot-swap flipped between this session's
                    # admission and its first decode row: its pages
                    # hold OLD-weight KV. flush() already dropped that
                    # generation's chains — re-indexing them here would
                    # hand stale KV to new-weight matches.
                    return
                # graft: allow(GL301): guarded by the pool lock just
                # above — the radix index shares the pool's Condition
                self.prefix_cache.insert(sess.prompt[:stem], sess._pages)
        # graft: allow(GL403): cache indexing is best-effort
        except Exception:
            logger.exception("prefix-cache insert failed (session %s)",
                             sess.id)

    # --------------------------------------------------- stepping chain
    def _next_row(self, sess: DecodeSession) -> np.ndarray:
        """The session's next request row, fixed width [1, 3 + chunk]:
        [slot, phase, n_valid, tok_0..]. Phase 0 rows carry up to
        `chunk` prompt-STEM tokens (`prompt[:-1]` — their logits are
        never read back); the phase 1 row carries the window's first
        input token: the last prompt token before anything is sampled,
        the previous window's last sample afterwards. The fused window
        derives everything else (sampling knobs, rng key, budget, EOS)
        from the session table at dispatch time."""
        row = np.zeros((1, 3 + self.prefill_chunk), np.float32)
        row[0, 0] = sess.slot
        stem = sess.prompt.size - 1
        if sess._off < stem:
            toks = sess.prompt[sess._off:min(stem, sess._off +
                                             self.prefill_chunk)]
            sess._off += toks.size
        else:
            if self.prefix_enabled and not sess._prefix_inserted:
                # first decode row => the last prefill future resolved:
                # the stem's pages hold final KV, index them now
                sess._prefix_inserted = True
                self._insert_prefix(sess)
            row[0, 1] = 1.0
            toks = np.asarray([sess.generated[-1] if sess.generated
                               else sess.prompt[-1]], np.int64)
        row[0, 2] = toks.size
        row[0, 3:3 + toks.size] = toks
        return row

    def _submit_next(self, sess: DecodeSession) -> None:
        with self._lock:
            if sess._finished:
                return      # aborted (shutdown/cancel) — stop the chain
        rem = sess.remaining_ms()
        if rem is not None and rem <= 0:
            self._finish(sess, error=DeadlineExceededError(
                f"session {sess.id} deadline passed"))
            return
        if sess._prefill_only and sess._off >= sess.prompt.size - 1:
            # disaggregated prefill role: the stem is fully prefilled —
            # index the pages (a fleet handoff exports them from the
            # radix) and finish without ever entering a decode window
            if self.prefix_enabled and not sess._prefix_inserted:
                sess._prefix_inserted = True
                self._insert_prefix(sess)
            self._finish(sess, outcome="completed")
            return
        row = self._next_row(sess)
        try:
            # explicit trace: resubmits run on scheduler worker threads,
            # where the edge's contextvar carrier is not in scope
            fut = self.scheduler.submit(self.decode_name, row,
                                        deadline_ms=rem,
                                        trace=sess.trace)
        except BaseException as e:
            self._finish(sess, error=e)
            return
        fut.add_done_callback(lambda f: self._on_step(sess, f))

    def _on_step(self, sess: DecodeSession, fut) -> None:
        """Future callback (runs on the scheduler worker): consume this
        round-trip's result, maybe finish, else chain the next row.
        Prefill legs return a zero count (their logits never left the
        device); window legs return the device-sampled tokens, so this
        callback only does bookkeeping — no host sampling. Every path
        must end in _finish or _submit_next — an escaped exception here
        would orphan the session's slot."""
        with self._lock:
            if sess._finished:
                return      # session was aborted while this step flew
        try:
            y = fut.result()
        except BaseException as e:
            self._finish(sess, error=e)
            return
        try:
            if sess.cancelled:
                self._finish(sess, outcome="cancelled")
                return
            n = int(np.asarray(y)[0, 0])
            if n <= 0:
                # mid-prefill (or a window whose lane was dropped):
                # nothing was sampled; keep the chain moving
                self._submit_next(sess)
                return
            toks = np.asarray(y)[0, 1:1 + n].astype(np.int64)
            now = time.monotonic()
            tid = sess.trace.trace_id if sess.trace is not None else None
            if sess.ttft_ms is None:
                sess.ttft_ms = (now - sess.opened_at) * 1000.0
                self._h_ttft.observe(sess.ttft_ms, exemplar=tid)
            else:
                # tokens arrive in a burst of n: the honest per-token
                # latency is the window gap amortized over the window
                gap_ms = (now - sess._last_tok_at) * 1000.0
                for _ in range(n):
                    self._h_itl.observe(gap_ms / n, exemplar=tid)
            sess._last_tok_at = now
            hit_eos, appended = False, 0
            for t in toks:
                tok = int(t)
                sess.generated.append(tok)
                appended += 1
                sess._events.put({"token": tok,
                                  "index": len(sess.generated) - 1})
                if sess.eos_id is not None and tok == sess.eos_id:
                    hit_eos = True
                    break   # the device stopped emitting after EOS too
            self._c_tokens.inc(appended)
            if hit_eos or len(sess.generated) >= sess.max_tokens:
                self._finish(sess, outcome="completed")
            else:
                self._submit_next(sess)
        except BaseException as e:
            self._finish(sess, error=e)

    def _finish(self, sess: DecodeSession, *, outcome: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        with self._lock:
            if sess._finished:
                return
            sess._finished = True
            self._sessions.pop(sess.id, None)
            n_active = len(self._sessions)
        if error is not None:
            outcome = ("expired" if isinstance(error, DeadlineExceededError)
                       else "failed")
        sess.outcome = outcome
        sess.error = error
        if sess.trace is not None:
            reqtrace.record_span(
                sess.trace.trace_id, "session.close",
                parent_id=sess.trace.span_id, session=sess.id,
                slot=sess.slot, outcome=outcome,
                tokens=len(sess.generated),
                error=None if error is None else type(error).__name__)
        self.pool.free(sess.slot)
        if self.prefix_enabled and sess._pages:
            # release the session's page references — free() only wiped
            # the slot's table/pos rows. Pages the radix index adopted
            # survive (its own refcount keeps them); purely private
            # pages drop to zero and return to the free list.
            with self.pool.lock():
                for p in sess._pages:
                    self.pool.page_unref_locked(p)
            sess._pages = []
        if self.draft_pool is not None:
            # lockstep draft slot: zero the mirror row for the next
            # tenant (reset, not free — the draft pool's free list is
            # deliberately unused)
            self.draft_pool.reset(sess.slot)
        self._c_out[outcome].inc()
        self._g_active.set(n_active)
        try:
            from deeplearning4j_tpu.observe import get_flight
            get_flight().record(
                "session_close", model=self.model, session=sess.id,
                outcome=outcome, tokens=len(sess.generated),
                error=None if error is None else type(error).__name__)
        # graft: allow(GL403): breadcrumbs are best-effort
        except Exception:
            pass
        if error is not None:
            sess._events.put({"error": str(error), "outcome": outcome})
        else:
            sess._events.put({"done": True, "outcome": outcome,
                              "tokens": len(sess.generated)})
        sess.done.set()

    # ------------------------------------------------- scheduler runner
    def run_batch(self, xs) -> np.ndarray:
        """The decode endpoint's data plane. `xs` is a stack of session
        rows ([k, 3+chunk], possibly from k different sessions — this
        coalescing IS continuous batching, and prefill rows co-batch
        with decode windows). At most two jitted programs run under the
        pool lock: one `session_step` over the prefill lanes (logits
        stay on device — prefill pays NO host sync), then one
        `session_decode_window` advancing every decoding lane K tokens
        with on-device sampling. Returns one result row per request
        row: `[count, tok_0..tok_{K-1}]` — count 0 for prefill legs.

        Speculating, the window half becomes draft-propose + target-
        verify (plus a mirrored draft prefill), accept/reject stays on
        device, and the ONE host sync per window reads back the verify's
        packed [S, spec_k+4] rows — emit/accept counts, catch-up token
        and emitted tokens together, so speculation never adds a sync."""
        xs = np.asarray(xs)
        if xs.ndim != 2 or xs.shape[1] != 3 + self.prefill_chunk:
            raise ValueError(
                f"decode rows must be [k, {3 + self.prefill_chunk}], "
                f"got {xs.shape}")
        k = xs.shape[0]
        # fan-in handoff: the scheduler worker opened a dispatch window
        # iff at least one co-batched row belongs to a sampled trace —
        # None here keeps the sampled-off path allocation-free
        dtrace = reqtrace.active_dispatch()
        t0 = time.perf_counter() if dtrace is not None else 0.0
        slots_idx = xs[:, 0].astype(np.int64)
        phase = xs[:, 1].astype(np.int64)
        nvalid = xs[:, 2].astype(np.int64)
        pre = np.nonzero(phase == 0)[0]
        dec = np.nonzero(phase == 1)[0]
        S, K = self.pool.slots, self.fused_k
        # a spec window can emit up to spec_k accepted drafts plus the
        # correction/bonus token; plain windows top out at K
        W = (self.spec_k + 1) if self.spec_enabled else K
        ys = np.zeros((k, 1 + W), np.float32)

        # prefill scatter: [S, bucket] chunk step, inactive lanes masked
        bucket = 0
        if pre.size:
            need = int(nvalid[pre].max())
            bucket = min(b for b in self.buckets if b >= need)
            tok = np.zeros((S, bucket), np.int64)
            val = np.zeros((S, bucket), np.float32)
        act_p = np.zeros((S,), bool)
        for i in pre:
            s, n = int(slots_idx[i]), int(nvalid[i])
            tok[s, :n] = xs[i, 3:3 + n].astype(np.int64)
            val[s, :n] = 1.0
            act_p[s] = True

        # window lanes: per-lane sampling knobs / keys / budgets from
        # the session table. Reading session fields here is safe — each
        # session has exactly one row in flight (this one), so nothing
        # mutates them concurrently.
        act_d = np.zeros((S,), bool)
        by_slot: Dict[int, DecodeSession] = {}
        if dec.size:
            with self._lock:
                by_slot = {s.slot: s for s in self._sessions.values()}
            tok0 = np.zeros((S,), np.int64)
            lane_params: List[Optional[SamplingParams]] = [None] * S
            keys = np.zeros((S, 2), np.uint32)
            offs = np.zeros((S,), np.int32)
            buds = np.zeros((S,), np.int32)
            eos = np.full((S,), -1, np.int32)
            rew = np.zeros((S,), np.int32)
            ptk = np.zeros((S,), np.int32)
            pvl = np.zeros((S,), bool)
            for i in dec:
                s = int(slots_idx[i])
                sess = by_slot.get(s)
                if sess is None:
                    continue    # finished while the row was queued
                act_d[s] = True
                tok0[s] = int(xs[i, 3])
                lane_params[s] = sess.params
                keys[s] = sess.base_key
                offs[s] = len(sess.generated)
                buds[s] = sess.max_tokens - len(sess.generated)
                if sess.eos_id is not None:
                    eos[s] = sess.eos_id
                if self.spec_enabled:
                    rew[s] = sess._spec_rewind
                    ptk[s] = sess._spec_pre_tok
                    pvl[s] = sess._spec_pre_valid
            temps, tks, tps, grd = lane_param_arrays(lane_params,
                                                     self.vocab)

        toks_d = None
        packed_d = None
        with self.pool.lock():
            # drop rows whose slot was freed while the row was queued
            # (session aborted mid-flight): stepping a freed slot would
            # dirty carries the pool just reset for the next tenant.
            # Reading _active is safe here — we hold the pool lock.
            for i in range(k):
                s = int(slots_idx[i])
                if not self.pool._active[s]:
                    act_p[s] = False
                    act_d[s] = False
            net = self.pool.net
            carries = self.pool.carries
            if self._reshard is not None:
                self._witness_carries(net, carries)
            if pre.size and act_p.any():
                x = _encode(tok, self._encoding, self.vocab)
                _, carries = net.session_step(
                    x, carries, active=act_p, valid=val)
            if self.spec_enabled:
                # fixed lock order, target pool THEN draft pool — every
                # acquirer nests the draft inside the target, so the
                # pair can never deadlock (graft-lint lock-order pass)
                with self.draft_pool.lock():
                    dnet = self.draft_pool.net
                    dcarries = self.draft_pool.carries
                    if pre.size and act_p.any():
                        # mirrored prefill: the draft consumes the same
                        # prompt stem (logits stay on device here too)
                        _, dcarries = dnet.session_step(
                            x, dcarries, active=act_p, valid=val)
                    if dec.size and act_d.any():
                        d_toks, d_probs, dcarries = \
                            dnet.session_propose_window(
                                tok0, dcarries, active=act_d,
                                k=self.spec_k, temperature=temps,
                                top_k=tks, top_p=tps, greedy=grd,
                                keys=keys, offsets=offs, rewind=rew,
                                pre_tokens=ptk, pre_valid=pvl)
                        packed_d, carries = net.session_verify_window(
                            tok0, carries, active=act_d, k=self.spec_k,
                            draft_tokens=d_toks, draft_probs=d_probs,
                            temperature=temps, top_k=tks, top_p=tps,
                            greedy=grd, keys=keys, offsets=offs,
                            budgets=buds, eos_ids=eos)
                    self.draft_pool.swap_carries(dcarries)
            elif dec.size and act_d.any():
                toks_d, emits_d, carries = net.session_decode_window(
                    tok0, carries, active=act_d, k=K,
                    temperature=temps, top_k=tks, top_p=tps, greedy=grd,
                    keys=keys, offsets=offs, budgets=buds, eos_ids=eos)
            self.pool.swap_carries(carries)
        emit_n = {}
        acc_n = {}
        if packed_d is not None:
            # ONE host sync per speculative window, after both locks are
            # released: counts, the catch-up token and all emissions
            # ride the verify's packed rows — the draft adds NO sync.
            # graft: allow-sync(decode endpoint window readback — the
            # one intended host sync per K-token window)
            ph = np.asarray(packed_d)
            wtoks = wdraft = wacc = 0
            for i in dec:
                s = int(slots_idx[i])
                if not act_d[s]:
                    continue
                n = int(ph[s, 0])
                emit_n[s] = n
                # accepted drafts actually EMITTED this window: the
                # verify's acceptance count, clipped to the emit count —
                # a token-budget cut mid-window truncates an accepted
                # run, and acceptance accounting must follow the tokens
                # that left the device or /metrics' rate drifts
                acc = min(int(ph[s, 1]), n)
                acc_n[s] = acc
                ys[i, 0] = n
                ys[i, 1:1 + n] = ph[s, 3:3 + n]
                sess = by_slot.get(s)
                if sess is not None:
                    # next window's draft entry bookkeeping (safe: this
                    # was the session's one in-flight row)
                    sess._spec_rewind = max(self.spec_k - n, 0)
                    sess._spec_pre_valid = bool(n == self.spec_k + 1)
                    sess._spec_pre_tok = int(ph[s, 2])
                wtoks += n
                wdraft += self.spec_k
                wacc += acc
                self._h_accept.observe(acc / self.spec_k)
            self._c_windows.inc()
            self._c_window_tokens.inc(wtoks)
            self._c_draft_toks.inc(wdraft)
            self._c_accepted.inc(wacc)
        if toks_d is not None:
            # device->host sync AFTER releasing the pool lock: the next
            # dispatch can enqueue its programs while we read this one
            # back. Prefill legs never reach this — the fused window's
            # sampled tokens are the ONE intended host sync, and it
            # covers K tokens per lane.
            # graft: allow-sync(decode endpoint window readback — the
            # one intended host sync per K-token window)
            toks_h = np.asarray(toks_d)
            emits_h = np.asarray(emits_d)
            wtoks = 0
            for i in dec:
                s = int(slots_idx[i])
                if not act_d[s]:
                    continue
                n = int(emits_h[s].sum())
                emit_n[s] = n
                ys[i, 0] = n
                ys[i, 1:1 + K] = toks_h[s]
                wtoks += n
            self._c_windows.inc()
            self._c_window_tokens.inc(wtoks)
        self._c_disp.inc()
        self._c_rows.inc(k)
        if k >= 2:
            self._c_shared.inc()
        if dtrace is not None:
            self._trace_windows(dtrace, slots_idx, phase, nvalid, emit_n,
                                acc_n, bucket, k,
                                (time.perf_counter() - t0) * 1e3)
        return ys

    def _witness_carries(self, net, carries) -> None:
        """Reshard-witness seam (commsmon, GL802) for the decode
        dispatch: until the model axis ships (ROADMAP item 1), session
        carries are REPLICATED by contract — a committed non-replicated
        sharding on any carry leaf is exactly where GSPMD would insert a
        per-window reshard collective. No active mesh context means
        single-device semantics: nothing to check, zero cost."""
        from deeplearning4j_tpu.observe.commsmon import check_dispatch_args
        from deeplearning4j_tpu.parallel.mesh import current_mesh_context
        if current_mesh_context() is None:
            return
        check_dispatch_args(f"{type(net).__name__}.decode",
                            {"carries": (carries, ())},
                            witness=self._reshard)

    def _comm_totals(self) -> Optional[dict]:
        """Owner-level compiled-collective totals for the serving net's
        active jit cache (None when the ledger has priced nothing)."""
        try:
            from deeplearning4j_tpu.observe.watchdog import get_watchdog
            with self.pool.lock():
                net = self.pool.net
            tag = getattr(net._jit_cache, "owner_tag", None)
            if tag is None:
                return None
            return get_watchdog().owner_comm_totals(tag)
        # graft: allow(GL403): span decoration is best-effort by design
        except Exception:
            return None

    def _trace_windows(self, dtrace, slots_idx, phase, nvalid,
                       emit_n: dict, acc_n: dict, bucket: int, k: int,
                       dur_ms: float) -> None:
        """One `session.window` span per sampled row of this dispatch —
        the per-window leaf of the fan-in tree, parented on that trace's
        dispatch span. Decode spans carry per-token attrs (`tokens`
        emitted this window, the window length `win`, and the per-token
        `itl` exemplars land on the histogram from the callback);
        prefill spans carry the chunk size. Host scalars only (the span
        contract)."""
        with self._lock:
            by_slot = {s.slot: s for s in self._sessions.values()
                       if s.trace is not None}
        # comm ledger totals for the serving net, once per dispatch:
        # every window span of this dispatch carries the same owner-level
        # collective figures (host metadata; {} keeps attrs uniform)
        comm = self._comm_totals() or {}
        for i in range(slots_idx.shape[0]):
            s = int(slots_idx[i])
            sess = by_slot.get(s)
            if sess is None:
                continue
            sid = dtrace.span_ids.get(sess.trace.trace_id)
            if sid is None:
                continue        # co-batched with a different endpoint
            decode = int(phase[i]) == 1
            # one row is in flight per session, so `generated` still
            # reflects the state the row was built from: prefill chunks
            # all precede the first sampled token
            reqtrace.record_span(
                sess.trace.trace_id, "session.window", parent_id=sid,
                dur_ms=dur_ms, session=sess.id, slot=sess.slot,
                phase="decode" if decode else "prefill",
                step=len(sess.generated),
                win=int((self.spec_k if self.spec_enabled
                         else self.fused_k) if decode else nvalid[i]),
                tokens=int(emit_n.get(s, 0)), bucket=bucket, rows=k,
                spec=bool(self.spec_enabled and decode),
                accepted=int(acc_n.get(s, 0)),
                prefix_cache=int(sess._cached_len),
                comm_ops=int(comm.get("ops", 0)),
                comm_bytes=int(comm.get("wire_bytes", 0)),
                # graft: allow(GL701): span attribute reads one atomic
                # str reference; a concurrent hot-swap may label one
                # window with the outgoing kernel kind — harmless
                kernel=self._policy_kind, loop=self.loop_kind)

    # --------------------------------------------------------- hot-swap
    def _deploy_hook(self, phase: str, name: str, version, net) -> None:
        if phase == "warm":
            # canary: live sessions must be hostable on the candidate
            # (raises IncompatibleSessionSwapError → deploy rolls back,
            # sessions keep serving the current version), and its step
            # buckets compile NOW so the flip costs zero recompiles
            want = self._check_swap_compat(net)
            del want
            self._compile_buckets(net)
            return
        if phase == "flipped":
            self.pool.rebind(net)
            if self.prefix_enabled:
                # old-weight KV is meaningless to NEW sessions under the
                # new weights: flush every cached chain. Live sessions
                # keep their own page references and finish coherently
                # on the pages they hold (the migration contract).
                with self.pool.lock():
                    # graft: allow(GL301): guarded by the pool lock
                    # just above — _prefix_gen shares the Condition.
                    # Bump first so in-flight old-generation sessions
                    # can never re-index the chains flush() drops.
                    self._prefix_gen += 1
                    self.prefix_cache.flush()
            with self._lock:
                self._net = net
                n = len(self._sessions)
            self.entry.net = net
            self.entry.version = version
            kind = self._policy_brief()     # takes _lock; compute first
            with self._lock:
                self._policy_kind = kind
            try:
                from deeplearning4j_tpu.observe import get_flight
                get_flight().record("decode_sessions_migrated",
                                    model=name, version=version,
                                    live_sessions=n)
            # graft: allow(GL403): breadcrumbs are best-effort
            except Exception:
                pass
            logger.info("decode sessions migrated to %s@%r (%d live)",
                        name, version, n)

    def _check_swap_compat(self, net):
        import jax
        if self.spec_enabled and not (
                hasattr(net, "spec_decode_capable")
                and net.spec_decode_capable()):
            raise IncompatibleSessionSwapError(
                f"deploy candidate for {self.model!r} cannot rewind its "
                f"decode caches (recurrent carries or rolling rings) — "
                f"this manager speculates; rolling back")
        if self.prefix_enabled and not (
                hasattr(net, "prefix_cache_capable")
                and net.prefix_cache_capable()):
            raise IncompatibleSessionSwapError(
                f"deploy candidate for {self.model!r} cannot page its "
                f"KV caches — this manager runs the prefix cache; "
                f"rolling back")
        want = jax.eval_shape(lambda: self._session_carries(net))
        have = jax.eval_shape(lambda: self.pool.carries)
        if jax.tree_util.tree_structure(want) != \
                jax.tree_util.tree_structure(have) or \
                [(l.shape, str(l.dtype))
                 for l in jax.tree_util.tree_leaves(want)] != \
                [(l.shape, str(l.dtype))
                 for l in jax.tree_util.tree_leaves(have)]:
            raise IncompatibleSessionSwapError(
                f"deploy candidate for {self.model!r} cannot host the "
                f"live session carries; rolling back")
        return want

    # -------------------------------------------------------- lifecycle
    def snapshot(self) -> dict:
        with self._lock:
            active = len(self._sessions)
        disp = int(self._c_disp.value)
        return {
            "model": self.model,
            "endpoint": self.decode_name,
            "sessions": {
                "active": active,
                "opened": int(self._c_opened.value),
                **{o: int(self._c_out[o].value) for o in _OUTCOMES},
            },
            "slots": self.pool.describe(),
            "tokens_streamed": int(self._c_tokens.value),
            "ttft_ms": self._h_ttft.percentiles(),
            "itl_ms": self._h_itl.percentiles(),
            "dispatches": {"total": disp,
                           "rows": int(self._c_rows.value),
                           "shared": int(self._c_shared.value),
                           "windows": int(self._c_windows.value),
                           "window_tokens":
                               int(self._c_window_tokens.value)},
            "buckets": list(self.buckets),
            "kernel_policy": self._kernel_policy(),
            "decode_loop": {"kind": self.loop_kind, "k": self.fused_k,
                            "reason": self._loop_reason},
            "spec_decode": {
                "enabled": self.spec_enabled, "k": self.spec_k,
                "reason": self._spec_reason, "draft": self.draft_name,
                "draft_tokens": int(self._c_draft_toks.value),
                "accepted_tokens": int(self._c_accepted.value),
                "acceptance_rate": (
                    round(int(self._c_accepted.value)
                          / int(self._c_draft_toks.value), 4)
                    if int(self._c_draft_toks.value) else None),
            },
            "kv_dtype": {"kind": self.kv_dtype,
                         "reason": self._kv_reason},
            "prefix_cache": self._prefix_snapshot(),
        }

    def _prefix_snapshot(self) -> dict:
        out = {"enabled": self.prefix_enabled,
               "page_len": self.page_len if self.prefix_enabled else 0,
               "reason": self._prefix_reason}
        if self.prefix_cache is not None:
            with self.pool.lock():
                out.update(self.prefix_cache.stats())
                out["pages"] = self.pool.pages
                out["pages_free"] = self.pool.pages_free_locked()
        return out

    def _policy_brief(self) -> str:
        """Compact kernel-policy verdict for span attributes: the sorted
        set of dispatch kinds across cached-attention layers."""
        kinds = sorted({p.get("kind") for p in self._kernel_policy()
                        if p.get("kind")})
        return ",".join(kinds) if kinds else "n/a"

    def _kernel_policy(self) -> list:
        """Which decode-attention kernel each cached-attention layer
        shape would dispatch to (kernel_defaults.decode_attention_policy
        — same call the layer makes per step), so snapshots show WHERE
        single-token steps run without reverse-engineering env + measured
        tables. Best-effort: policy evaluation must never take down
        /metrics."""
        try:
            from deeplearning4j_tpu.ops.kernel_defaults import (
                decode_attention_policy,
            )

            with self._lock:
                net = self._net
            seen, out = set(), []
            for layer in getattr(net, "layers", ()):
                heads = getattr(layer, "num_heads", None)
                if heads is None or not hasattr(layer, "decode_carry"):
                    continue
                # TransformerEncoderBlock carries num_kv_heads directly;
                # MultiHeadAttention resolves it via the _kv_heads prop
                hkv = getattr(layer, "_kv_heads", None) or getattr(
                    layer, "num_kv_heads", None) or heads
                key = (layer.max_cache, heads, hkv)
                if key in seen:
                    continue
                seen.add(key)
                pol = decode_attention_policy(*key, record=False)
                out.append({"layer": layer.name, "cache_len": key[0],
                            "heads": key[1], "kv_heads": key[2],
                            "kind": pol.kind, "reason": pol.reason})
            return out
        # graft: allow(GL403): snapshot decoration is best-effort
        except Exception:
            return []

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for every live session to finish (no new admissions are
        blocked — callers close admission first if they need that)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                live = list(self._sessions.values())
            if not live:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            live[0].done.wait(0.05)

    def shutdown(self) -> None:
        """Abort every live session (clients get a terminal error event)
        and detach from the registry. Called by registry.close() through
        the entry's runner seam, or directly."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            live = list(self._sessions.values())
        for sess in live:
            self._finish(sess, error=SchedulerClosedError(
                "decode session manager shut down"))
        try:
            self.registry.remove_deploy_hook(self.model, self._deploy_hook)
        # graft: allow(GL403): registry may already be closing
        except Exception:
            pass
