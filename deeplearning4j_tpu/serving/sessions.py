"""Stateful decode serving: per-request sessions over a shared KV slot
pool, stepped through the continuous-batching scheduler.

The old decode path (`utils/textgen.generate`) drives `rnn_time_step`,
which mutates MODEL-GLOBAL carries — one autoregressive stream per net,
and a server would have to dedicate a model replica per conversation.
This module turns decode into data: each session owns a SLOT in a
`KVSlotPool` (one batch row of a [slots, ...] carry tree), and every
step — prefill chunk or single-token decode — is submitted to the
`ContinuousBatchingScheduler` as an ordinary one-row request against a
dedicated `<model>@decode` endpoint. The scheduler coalesces whatever
rows are queued, the endpoint's `run_batch` scatters them into the
fixed [slots, bucket] step shape, runs ONE jitted `session_step`
(inactive lanes masked, RNN carries held, attention writes dropped),
and each session samples its next token in the future's done-callback
and immediately submits the next row. Sessions at different phases —
one mid-prefill, another deep into decode — share the same dispatch
and the same compiled program.

Shapes are the contract: every dispatch runs at bucket 1 (pure decode)
or bucket `prefill_chunk` (any prefill present), both warmed at
construction, so session churn causes ZERO recompiles — the watchdog
stays quiet (see PERF_NOTES). TTFT/ITL histograms, token counters and
shared-dispatch counters ride the server's metrics registry so the
closed-loop bench can reconcile its client-side numbers.

Hot-swap: the manager subscribes to registry deploy hooks for its base
model. In the "warm" phase it verifies the candidate can host the live
carry tree and pre-compiles its session-step buckets (raising rides
the normal rollback — sessions keep serving the old version); in the
"flipped" phase it rebinds the pool, migrating every live session onto
the new weights mid-stream instead of dropping them.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.observe import reqtrace
from deeplearning4j_tpu.serving.kv_pool import (
    IncompatibleSessionSwapError, KVSlotPool, SlotPoolExhaustedError,
)
from deeplearning4j_tpu.serving.registry import ModelEntry
from deeplearning4j_tpu.serving.scheduler import (
    DeadlineExceededError, RequestShedError, SchedulerClosedError,
)
from deeplearning4j_tpu.utils.sampling import SamplingParams, sample_next
from deeplearning4j_tpu.utils.textgen import (
    _encode, _input_encoding, _resolve_net,
)

logger = logging.getLogger("deeplearning4j_tpu")

_OUTCOMES = ("completed", "cancelled", "expired", "failed")


class DecodeSession:
    """One streaming generation: a slot, a cursor into the prompt, the
    sampling state, and a queue of token events the client drains."""

    def __init__(self, sid: str, slot: int, prompt: np.ndarray, *,
                 max_tokens: int, params: SamplingParams,
                 seed: Optional[int], deadline_ms: Optional[float],
                 eos_id: Optional[int], trace=None):
        self.id = sid
        self.slot = slot
        self.prompt = prompt
        # sampled requests carry their TraceContext through every
        # resubmitted step; None on the sampled-off fast path
        self.trace = trace
        self.max_tokens = int(max_tokens)
        self.params = params
        self.rng = np.random.default_rng(seed)
        self.eos_id = eos_id
        self.opened_at = time.monotonic()
        self.deadline = (None if deadline_ms is None
                         else self.opened_at + deadline_ms / 1000.0)
        self.generated: List[int] = []
        self.outcome: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.ttft_ms: Optional[float] = None
        self.done = threading.Event()
        self.cancelled = False
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._off = 0              # prompt tokens already submitted
        self._last_tok_at: Optional[float] = None
        self._finished = False     # guarded by the manager lock

    # -------------------------------------------------------- client API
    def stream(self, timeout: Optional[float] = None):
        """Yield token events as they arrive: `{"token", "index"}` per
        token, then exactly one terminal event (`{"done": ...}` or
        `{"error": ...}`). Raises queue.Empty if `timeout` seconds pass
        without an event (a stalled-stream guard for clients)."""
        while True:
            ev = self._events.get(timeout=timeout)
            yield ev
            if "done" in ev or "error" in ev:
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the session finishes; returns the generated token
        ids, or raises the session's error (deadline, shed, crash)."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"session {self.id} still running")
        if self.error is not None:
            raise self.error
        return list(self.generated)

    def cancel(self) -> None:
        """Request cancellation; honored at the next step boundary (there
        is always at most one step in flight per session)."""
        self.cancelled = True

    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - time.monotonic()) * 1000.0

    def describe(self) -> dict:
        return {"id": self.id, "slot": self.slot,
                "prompt_len": int(self.prompt.size),
                "generated": len(self.generated),
                "max_tokens": self.max_tokens,
                "ttft_ms": self.ttft_ms,
                "outcome": self.outcome,
                "trace_id": (self.trace.trace_id
                             if self.trace is not None else None)}


class DecodeSessionManager:
    """Owns the slot pool, the `<model>@decode` endpoint, and the
    callback chain that steps every live session."""

    def __init__(self, registry, scheduler, model: str = "default", *,
                 slots: int = 4, prefill_chunk: int = 8,
                 metrics=None, warm: bool = True):
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        base = registry.get(model)      # KeyError if not deployed
        if not hasattr(base.net, "session_carries"):
            raise TypeError(
                f"decode sessions need a net with session_carries() "
                f"(MultiLayerNetwork); got {type(base.net).__name__}")
        self.registry = registry
        self.scheduler = scheduler
        self.model = model
        self.decode_name = f"{model}@decode"
        self.prefill_chunk = int(prefill_chunk)
        self.buckets = sorted({1, self.prefill_chunk})
        self._lock = threading.Lock()
        self._net = base.net
        self._sessions: Dict[str, DecodeSession] = {}
        self._sid = itertools.count(1)
        self._closed = False

        first, vocab = _resolve_net(base.net)
        self.vocab = int(vocab)
        self._encoding = _input_encoding(first)
        self._limit = base.net.decode_limit()

        if metrics is None:
            from deeplearning4j_tpu.observe import get_registry
            metrics = get_registry()
        self.metrics = metrics
        self.pool = KVSlotPool(base.net, slots, model=model,
                               metrics=metrics)
        self._g_active = metrics.gauge("serving_sessions_active",
                                       model=model)
        self._c_opened = metrics.counter("serving_sessions_total",
                                         model=model, outcome="opened")
        self._c_out = {o: metrics.counter("serving_sessions_total",
                                          model=model, outcome=o)
                       for o in _OUTCOMES}
        self._c_tokens = metrics.counter("serving_decode_tokens_total",
                                         model=model)
        self._h_ttft = metrics.histogram("serving_ttft_ms", model=model)
        self._h_itl = metrics.histogram("serving_itl_ms", model=model)
        self._c_disp = metrics.counter("serving_decode_dispatches_total",
                                       model=model)
        self._c_rows = metrics.counter(
            "serving_decode_dispatch_rows_total", model=model)
        self._c_shared = metrics.counter(
            "serving_decode_shared_dispatches_total", model=model)

        # the decode endpoint: an ordinary registry entry whose "runner"
        # is this manager — scheduler dispatch, drain-on-retire and
        # registry.close() all work unchanged
        self.entry = registry.register_entry(
            self.decode_name,
            ModelEntry(self.decode_name, getattr(base, "version", None),
                       base.net, runner=self))
        registry.add_deploy_hook(model, self._deploy_hook)
        # kernel-policy verdict cached once (and refreshed on hot-swap):
        # session-step spans stamp it per ITL step, and re-deriving it
        # per dispatch would price policy evaluation into the hot path
        self._policy_kind = self._policy_brief()
        if warm:
            self.warmup()

    # ------------------------------------------------------------ warmup
    def _feat_dim(self) -> int:
        return 1 if self._encoding == "ids" else self.vocab

    def _compile_buckets(self, net) -> None:
        """Run one all-lanes-inactive step per bucket so every dispatch
        shape this manager will ever use is compiled before traffic (the
        zero-recompiles-after-warmup contract the bench asserts)."""
        carries = net.session_carries(self.pool.slots)
        S, F = self.pool.slots, self._feat_dim()
        act = np.zeros((S,), bool)
        for b in self.buckets:
            x = np.zeros((S, b, F), np.float32)
            val = np.zeros((S, b), np.float32)
            out, _ = net.session_step(x, carries, active=act, valid=val)
            # materialize: compile time must land in warmup, not on the
            # first live dispatch
            # graft: allow-sync(warmup barrier — pre-traffic by design)
            np.asarray(out)

    def warmup(self) -> None:
        self._compile_buckets(self.pool.net)

    # ---------------------------------------------------------- sessions
    def open_session(self, prompt_ids, *, max_tokens: int = 16,
                     temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     greedy: bool = False, seed: Optional[int] = None,
                     deadline_ms: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     alloc_timeout_s: float = 0.0,
                     trace=None) -> DecodeSession:
        """Admit one generation: claim a slot (SlotPoolExhaustedError →
        503 upstream), validate the token budget against the net's
        decode limit, and kick off the prefill→decode callback chain.
        Returns immediately; consume via `stream()`/`result()`."""
        prompt = np.asarray(prompt_ids, dtype=np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt_ids must contain at least one token")
        if prompt.min() < 0 or prompt.max() >= self.vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {self.vocab})")
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        params = SamplingParams(temperature=temperature, top_k=top_k,
                                top_p=top_p, greedy=greedy)
        if self._limit is not None and \
                int(prompt.size) + int(max_tokens) > self._limit:
            raise ValueError(
                f"prompt ({prompt.size}) + max_tokens ({max_tokens}) "
                f"exceeds the decode budget of {self._limit} for this "
                f"net (non-rolling cache)")
        with self._lock:
            if self._closed:
                raise SchedulerClosedError("session manager is shut down")
        slot = self.pool.alloc(alloc_timeout_s)
        sess = DecodeSession(
            f"s{next(self._sid):06d}", slot, prompt,
            max_tokens=max_tokens, params=params, seed=seed,
            deadline_ms=deadline_ms, eos_id=eos_id, trace=trace)
        with self._lock:
            self._sessions[sess.id] = sess
        self._c_opened.inc()
        self._g_active.set(len(self._sessions))
        try:
            from deeplearning4j_tpu.observe import get_flight
            get_flight().record("session_open", model=self.model,
                                session=sess.id, slot=slot,
                                prompt_len=int(prompt.size),
                                max_tokens=int(max_tokens))
        # graft: allow(GL403): breadcrumbs are best-effort
        except Exception:
            pass
        self._submit_next(sess)
        return sess

    def get_session(self, sid: str) -> Optional[DecodeSession]:
        with self._lock:
            return self._sessions.get(sid)

    def cancel(self, sid: str) -> bool:
        sess = self.get_session(sid)
        if sess is None:
            return False
        sess.cancel()
        return True

    # --------------------------------------------------- stepping chain
    def _next_row(self, sess: DecodeSession) -> np.ndarray:
        """The session's next request row, fixed width [1, 2 + chunk]:
        [slot, n_valid, tok_0..]. Prefill rows carry up to `chunk`
        prompt tokens; decode rows carry the last sampled token."""
        row = np.zeros((1, 2 + self.prefill_chunk), np.float32)
        row[0, 0] = sess.slot
        if sess._off < sess.prompt.size:
            toks = sess.prompt[sess._off:sess._off + self.prefill_chunk]
            sess._off += toks.size
        else:
            toks = np.asarray([sess.generated[-1]], np.int64)
        row[0, 1] = toks.size
        row[0, 2:2 + toks.size] = toks
        return row

    def _submit_next(self, sess: DecodeSession) -> None:
        with self._lock:
            if sess._finished:
                return      # aborted (shutdown/cancel) — stop the chain
        rem = sess.remaining_ms()
        if rem is not None and rem <= 0:
            self._finish(sess, error=DeadlineExceededError(
                f"session {sess.id} deadline passed"))
            return
        row = self._next_row(sess)
        try:
            # explicit trace: resubmits run on scheduler worker threads,
            # where the edge's contextvar carrier is not in scope
            fut = self.scheduler.submit(self.decode_name, row,
                                        deadline_ms=rem,
                                        trace=sess.trace)
        except BaseException as e:
            self._finish(sess, error=e)
            return
        fut.add_done_callback(lambda f: self._on_step(sess, f))

    def _on_step(self, sess: DecodeSession, fut) -> None:
        """Future callback (runs on the scheduler worker): consume this
        step's logits, maybe sample, maybe finish, else chain the next
        row. Every path must end in _finish or _submit_next — an escaped
        exception here would orphan the session's slot."""
        with self._lock:
            if sess._finished:
                return      # session was aborted while this step flew
        try:
            y = fut.result()
        except BaseException as e:
            self._finish(sess, error=e)
            return
        try:
            if sess.cancelled:
                self._finish(sess, outcome="cancelled")
                return
            if sess._off < sess.prompt.size:
                # mid-prefill: the logits are positional garbage until
                # the last prompt token lands; keep feeding chunks
                self._submit_next(sess)
                return
            p = np.asarray(y, np.float64)[0]
            tok = int(sample_next(p[None], sess.params, sess.rng)[0])
            now = time.monotonic()
            tid = sess.trace.trace_id if sess.trace is not None else None
            if sess.ttft_ms is None:
                sess.ttft_ms = (now - sess.opened_at) * 1000.0
                self._h_ttft.observe(sess.ttft_ms, exemplar=tid)
            else:
                self._h_itl.observe((now - sess._last_tok_at) * 1000.0,
                                    exemplar=tid)
            sess._last_tok_at = now
            sess.generated.append(tok)
            self._c_tokens.inc()
            sess._events.put({"token": tok,
                              "index": len(sess.generated) - 1})
            if (sess.eos_id is not None and tok == sess.eos_id) or \
                    len(sess.generated) >= sess.max_tokens:
                self._finish(sess, outcome="completed")
            else:
                self._submit_next(sess)
        except BaseException as e:
            self._finish(sess, error=e)

    def _finish(self, sess: DecodeSession, *, outcome: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        with self._lock:
            if sess._finished:
                return
            sess._finished = True
            self._sessions.pop(sess.id, None)
            n_active = len(self._sessions)
        if error is not None:
            outcome = ("expired" if isinstance(error, DeadlineExceededError)
                       else "failed")
        sess.outcome = outcome
        sess.error = error
        if sess.trace is not None:
            reqtrace.record_span(
                sess.trace.trace_id, "session.close",
                parent_id=sess.trace.span_id, session=sess.id,
                slot=sess.slot, outcome=outcome,
                tokens=len(sess.generated),
                error=None if error is None else type(error).__name__)
        self.pool.free(sess.slot)
        self._c_out[outcome].inc()
        self._g_active.set(n_active)
        try:
            from deeplearning4j_tpu.observe import get_flight
            get_flight().record(
                "session_close", model=self.model, session=sess.id,
                outcome=outcome, tokens=len(sess.generated),
                error=None if error is None else type(error).__name__)
        # graft: allow(GL403): breadcrumbs are best-effort
        except Exception:
            pass
        if error is not None:
            sess._events.put({"error": str(error), "outcome": outcome})
        else:
            sess._events.put({"done": True, "outcome": outcome,
                              "tokens": len(sess.generated)})
        sess.done.set()

    # ------------------------------------------------- scheduler runner
    def run_batch(self, xs) -> np.ndarray:
        """The decode endpoint's data plane. `xs` is a stack of session
        rows ([k, 2+chunk], possibly from k different sessions — this
        coalescing IS continuous batching). Scatter into the [slots,
        bucket] step shape, run the one shared jitted step under the
        pool lock, gather each row's last-valid-position logits."""
        xs = np.asarray(xs)
        if xs.ndim != 2 or xs.shape[1] != 2 + self.prefill_chunk:
            raise ValueError(
                f"decode rows must be [k, {2 + self.prefill_chunk}], "
                f"got {xs.shape}")
        k = xs.shape[0]
        # fan-in handoff: the scheduler worker opened a dispatch window
        # iff at least one co-batched row belongs to a sampled trace —
        # None here keeps the sampled-off path allocation-free
        dtrace = reqtrace.active_dispatch()
        t0 = time.perf_counter() if dtrace is not None else 0.0
        slots_idx = xs[:, 0].astype(np.int64)
        nvalid = xs[:, 1].astype(np.int64)
        need = int(nvalid.max())
        bucket = min(b for b in self.buckets if b >= need)
        S = self.pool.slots
        tok = np.zeros((S, bucket), np.int64)
        val = np.zeros((S, bucket), np.float32)
        act = np.zeros((S,), bool)
        for i in range(k):
            s, n = int(slots_idx[i]), int(nvalid[i])
            tok[s, :n] = xs[i, 2:2 + n].astype(np.int64)
            val[s, :n] = 1.0
            act[s] = True
        x = _encode(tok, self._encoding, self.vocab)
        with self.pool.lock():
            # drop rows whose slot was freed while the row was queued
            # (session aborted mid-flight): stepping a freed slot would
            # dirty carries the pool just reset for the next tenant.
            # Reading _active is safe here — we hold the pool lock.
            for i in range(k):
                if not self.pool._active[int(slots_idx[i])]:
                    act[int(slots_idx[i])] = False
            net = self.pool.net
            out, new_carries = net.session_step(
                x, self.pool.carries, active=act, valid=val)
            self.pool.swap_carries(new_carries)
        # device->host sync AFTER releasing the pool lock: the next
        # dispatch can enqueue its step while we read this one back
        # graft: allow-sync(decode endpoint result readback — the one
        # intended host sync per dispatch)
        out = np.asarray(out)
        ys = out[slots_idx, np.maximum(nvalid - 1, 0), :]
        self._c_disp.inc()
        self._c_rows.inc(k)
        if k >= 2:
            self._c_shared.inc()
        if dtrace is not None:
            self._trace_steps(dtrace, slots_idx, bucket, k,
                              (time.perf_counter() - t0) * 1e3)
        return ys

    def _trace_steps(self, dtrace, slots_idx, bucket: int, k: int,
                     dur_ms: float) -> None:
        """One `session.step` span per sampled row of this dispatch —
        the ITL-step leaf of the fan-in tree, parented on that trace's
        dispatch span and stamped with the slot id and the cached
        kernel-policy verdict. Host scalars only (the span contract)."""
        with self._lock:
            by_slot = {s.slot: s for s in self._sessions.values()
                       if s.trace is not None}
        for i in range(slots_idx.shape[0]):
            sess = by_slot.get(int(slots_idx[i]))
            if sess is None:
                continue
            sid = dtrace.span_ids.get(sess.trace.trace_id)
            if sid is None:
                continue        # co-batched with a different endpoint
            # one step is in flight per session, so `generated` still
            # reflects the state the row was built from: prefill chunks
            # all precede the first sampled token
            reqtrace.record_span(
                sess.trace.trace_id, "session.step", parent_id=sid,
                dur_ms=dur_ms, session=sess.id, slot=sess.slot,
                phase="prefill" if not sess.generated else "decode",
                step=len(sess.generated), bucket=bucket, rows=k,
                kernel=self._policy_kind)

    # --------------------------------------------------------- hot-swap
    def _deploy_hook(self, phase: str, name: str, version, net) -> None:
        if phase == "warm":
            # canary: live sessions must be hostable on the candidate
            # (raises IncompatibleSessionSwapError → deploy rolls back,
            # sessions keep serving the current version), and its step
            # buckets compile NOW so the flip costs zero recompiles
            want = self._check_swap_compat(net)
            del want
            self._compile_buckets(net)
            return
        if phase == "flipped":
            self.pool.rebind(net)
            with self._lock:
                self._net = net
                n = len(self._sessions)
            self.entry.net = net
            self.entry.version = version
            kind = self._policy_brief()     # takes _lock; compute first
            with self._lock:
                self._policy_kind = kind
            try:
                from deeplearning4j_tpu.observe import get_flight
                get_flight().record("decode_sessions_migrated",
                                    model=name, version=version,
                                    live_sessions=n)
            # graft: allow(GL403): breadcrumbs are best-effort
            except Exception:
                pass
            logger.info("decode sessions migrated to %s@%r (%d live)",
                        name, version, n)

    def _check_swap_compat(self, net):
        import jax
        want = jax.eval_shape(
            lambda: net.session_carries(self.pool.slots))
        have = jax.eval_shape(lambda: self.pool.carries)
        if jax.tree_util.tree_structure(want) != \
                jax.tree_util.tree_structure(have) or \
                [(l.shape, str(l.dtype))
                 for l in jax.tree_util.tree_leaves(want)] != \
                [(l.shape, str(l.dtype))
                 for l in jax.tree_util.tree_leaves(have)]:
            raise IncompatibleSessionSwapError(
                f"deploy candidate for {self.model!r} cannot host the "
                f"live session carries; rolling back")
        return want

    # -------------------------------------------------------- lifecycle
    def snapshot(self) -> dict:
        with self._lock:
            active = len(self._sessions)
        disp = int(self._c_disp.value)
        return {
            "model": self.model,
            "endpoint": self.decode_name,
            "sessions": {
                "active": active,
                "opened": int(self._c_opened.value),
                **{o: int(self._c_out[o].value) for o in _OUTCOMES},
            },
            "slots": self.pool.describe(),
            "tokens_streamed": int(self._c_tokens.value),
            "ttft_ms": self._h_ttft.percentiles(),
            "itl_ms": self._h_itl.percentiles(),
            "dispatches": {"total": disp,
                           "rows": int(self._c_rows.value),
                           "shared": int(self._c_shared.value)},
            "buckets": list(self.buckets),
            "kernel_policy": self._kernel_policy(),
        }

    def _policy_brief(self) -> str:
        """Compact kernel-policy verdict for span attributes: the sorted
        set of dispatch kinds across cached-attention layers."""
        kinds = sorted({p.get("kind") for p in self._kernel_policy()
                        if p.get("kind")})
        return ",".join(kinds) if kinds else "n/a"

    def _kernel_policy(self) -> list:
        """Which decode-attention kernel each cached-attention layer
        shape would dispatch to (kernel_defaults.decode_attention_policy
        — same call the layer makes per step), so snapshots show WHERE
        single-token steps run without reverse-engineering env + measured
        tables. Best-effort: policy evaluation must never take down
        /metrics."""
        try:
            from deeplearning4j_tpu.ops.kernel_defaults import (
                decode_attention_policy,
            )

            with self._lock:
                net = self._net
            seen, out = set(), []
            for layer in getattr(net, "layers", ()):
                heads = getattr(layer, "num_heads", None)
                if heads is None or not hasattr(layer, "decode_carry"):
                    continue
                # TransformerEncoderBlock carries num_kv_heads directly;
                # MultiHeadAttention resolves it via the _kv_heads prop
                hkv = getattr(layer, "_kv_heads", None) or getattr(
                    layer, "num_kv_heads", None) or heads
                key = (layer.max_cache, heads, hkv)
                if key in seen:
                    continue
                seen.add(key)
                pol = decode_attention_policy(*key, record=False)
                out.append({"layer": layer.name, "cache_len": key[0],
                            "heads": key[1], "kv_heads": key[2],
                            "kind": pol.kind, "reason": pol.reason})
            return out
        # graft: allow(GL403): snapshot decoration is best-effort
        except Exception:
            return []

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for every live session to finish (no new admissions are
        blocked — callers close admission first if they need that)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                live = list(self._sessions.values())
            if not live:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            live[0].done.wait(0.05)

    def shutdown(self) -> None:
        """Abort every live session (clients get a terminal error event)
        and detach from the registry. Called by registry.close() through
        the entry's runner seam, or directly."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            live = list(self._sessions.values())
        for sess in live:
            self._finish(sess, error=SchedulerClosedError(
                "decode session manager shut down"))
        try:
            self.registry.remove_deploy_hook(self.model, self._deploy_hook)
        # graft: allow(GL403): registry may already be closing
        except Exception:
            pass
