"""REST model-inference server backed by ParallelInference.

Reference precedent: the reference embeds `ParallelInference` in user code;
this exposes it over HTTP (shared plumbing in serving/http_base.py) like
the nearest-neighbor server exposes VPTree:
  POST /output  {"ndarray": [[...], ...]}  → {"output": [[...], ...]}
  GET  /healthz
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.parallel.inference import InferenceMode, ParallelInference
from deeplearning4j_tpu.serving.http_base import JsonHttpServer


class InferenceServer(JsonHttpServer):
    def __init__(self, net, *, port: int = 9001, batched: bool = True,
                 max_batch_size: int = 64):
        super().__init__(port=port)
        self.pi = ParallelInference(
            net,
            mode=InferenceMode.BATCHED if batched else InferenceMode.INPLACE,
            max_batch_size=max_batch_size)

    def _output(self, req: dict):
        x = np.asarray(req["ndarray"], np.float32)
        return {"output": np.asarray(self.pi.output(x)).tolist()}

    def post_routes(self):
        return {"/output": self._output}

    def stop(self):
        super().stop()
        self.pi.shutdown()
