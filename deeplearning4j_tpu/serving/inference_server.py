"""Model-serving control plane: REST front end over the ModelRegistry +
continuous-batching scheduler.

Grown from the original 37-line single-model wrapper into the serving
subsystem the ROADMAP's "heavy traffic" north star needs: a multi-model
registry with zero-downtime hot-swap, admission control with explicit
backpressure semantics, and an observability surface.

  POST /output   {"ndarray": [[...], ...], "model": "name"?,
                  "deadline_ms": 250?}
                 → {"output": [[...], ...], "model": ..., "version": ...}
                 errors: 400 client fault, 503 shed/draining,
                 504 deadline exceeded, 500 server fault
  POST /generate {"prompt_ids": [...], "model"?, "max_tokens"?,
                  "temperature"/"top_k"/"top_p"/"greedy"?, "seed"?,
                  "deadline_ms"?, "eos_id"?, "stream"? (default true)}
                 → SSE token stream (one `data:` frame per token, then
                 a terminal done/error frame), or one JSON body with
                 "stream": false. Needs decode sessions enabled
                 (`decode_slots=N` or enable_decode_sessions()); slot
                 exhaustion → 503. Client disconnect cancels.
  POST /generate/cancel {"session": id, "model"?} → {"cancelled": bool}
  GET  /sessions → per-model decode snapshot (slots, session outcomes,
                 streamed tokens, TTFT/ITL, shared-dispatch counters)
  GET  /models   → per-model {version, served, inflight, deployments}
  GET  /metrics  → ServingStats snapshot (queue depth, batch-occupancy
                 histogram, p50/p95/p99 latency, shed count, per-model
                 totals). Content-negotiated: JSON by default;
                 Prometheus text exposition (Content-Type
                 `text/plain; version=0.0.4`) when the scraper sends
                 `Accept: text/plain` / openmetrics or
                 `?format=prometheus` — one renderer over the shared
                 `observe.MetricsRegistry`, so passing
                 `metrics=observe.get_registry()` publishes training
                 metrics through the same scrape endpoint
  GET  /healthz  → {"status": "ok" | "degraded", "reasons": [...]} —
                 degraded when the admission queue passes
                 `degraded_fraction` of capacity, the recompile
                 watchdog tripped on one of this server's jit owners,
                 a slot worker is crash-looping, or an SLO is firing
                 (reason list names each cause)
  GET  /series   → sampled telemetry time-series windows (needs
                 `slo=True` / enable_slo(); `?window=60&prefix=serving_`
                 filters). One point per registry series per sampler
                 tick; histograms appear as `:count`/`:p50/:p95/:p99`
  GET  /slo      → the SLO engine's last evaluation: per-objective
                 burn rates (fast/slow windows), firing state, breach
                 counts + forced-trace ids, anomaly-watch warnings;
                 `?refresh=1` forces a tick first
  GET  /devices  → live per-device telemetry (one DeviceMonitor sample:
                 memory_stats bytes in-use/peak/limit where the backend
                 reports them, live-array counts everywhere)
  GET  /flight   → the FlightRecorder ring: recent spans/compiles/
                 device samples plus paths of any crash dumps written
  GET  /trace/{id} → reconstructed span tree for one sampled request
                 (HTTP root → queue.wait → shared dispatch →
                 session.step leaves); `GET /trace/` lists stored ids.
                 Sampling: DL4J_TPU_TRACE_SAMPLE rate at the edge;
                 shed/expired/worker-crash requests always trace, and
                 error payloads carry their `trace_id`.

Dispatch modes:
  batched=True,  scheduler="continuous"  (default) — the
      ContinuousBatchingScheduler: requests join the next device
      dispatch as soon as a slot frees
  batched=True,  scheduler="collect" — the legacy fixed
      collect-then-run loop (ParallelInference BATCHED); kept as the
      bench baseline (`bench.py --serving` compares the two)
  batched=False — direct synchronous dispatch per HTTP thread
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observe import reqtrace
from deeplearning4j_tpu.observe.registry import PROMETHEUS_CONTENT_TYPE
from deeplearning4j_tpu.parallel.inference import InferenceMode
from deeplearning4j_tpu.serving.http_base import (
    HttpError, JsonHttpServer, StreamResponse, TextResponse,
)
from deeplearning4j_tpu.serving.kv_pool import SlotPoolExhaustedError
from deeplearning4j_tpu.serving.metrics import ServingStats
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.scheduler import (
    AdmissionPolicy, ContinuousBatchingScheduler, DeadlineExceededError,
    RequestShedError, SchedulerClosedError,
)

DEFAULT_MODEL = "default"


class InferenceServer(JsonHttpServer):
    """One HTTP server, many models. `net` is a convenience: deployed as
    ("default", version 1) without warmup (first request compiles, as
    the original single-model server did); `deploy()` warms by default.
    """

    def __init__(self, net=None, *, port: int = 0, batched: bool = True,
                 max_batch_size: int = 64,
                 registry: Optional[ModelRegistry] = None,
                 scheduler: str = "continuous",
                 admission: str = AdmissionPolicy.BLOCK,
                 queue_capacity: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 batch_buckets=None, collect_wait_ms: float = 5.0,
                 slots: int = 1, degraded_fraction: float = 0.8,
                 mesh=None, metrics=None, decode_slots: int = 0,
                 decode_prefill_chunk: int = 8,
                 decode_fused_k: Optional[int] = None,
                 decode_draft_net=None,
                 decode_spec_k: Optional[int] = None,
                 decode_kv_dtype: Optional[str] = None,
                 decode_page_len: Optional[int] = None,
                 slo: bool = False,
                 slo_objectives=None,
                 series_interval: Optional[float] = None):
        super().__init__(port=port)
        if scheduler not in ("continuous", "collect"):
            raise ValueError("scheduler must be 'continuous' or 'collect'")
        self.mode = ("continuous" if batched and scheduler == "continuous"
                     else "collect" if batched else "direct")
        # `metrics`: a shared observe.MetricsRegistry (e.g.
        # observe.get_registry()) so /metrics publishes the whole
        # process's telemetry; default is a private registry per server.
        self.stats = ServingStats(registry=metrics)
        self.degraded_fraction = degraded_fraction
        if registry is None:
            registry = ModelRegistry(
                mesh=mesh, max_batch_size=max_batch_size,
                batch_buckets=batch_buckets,
                runner_mode=(InferenceMode.BATCHED
                             if self.mode == "collect"
                             else InferenceMode.INPLACE),
                collect_wait_ms=collect_wait_ms)
        self.registry = registry
        self.scheduler = None
        if self.mode == "continuous":
            self.scheduler = ContinuousBatchingScheduler(
                registry, self.stats, max_batch_size=max_batch_size,
                queue_capacity=queue_capacity, policy=admission,
                default_deadline_ms=default_deadline_ms, slots=slots)
        self._decode = {}
        self._series_store = None
        self._sampler = None
        self._slo = None
        self._anomaly = None
        if slo:
            self.enable_slo(slos=slo_objectives,
                            interval=series_interval)
        if net is not None:
            self.registry.deploy(DEFAULT_MODEL, 1, net, warm=False)
            # decode_slots > 0 turns on stateful decode serving for the
            # convenience model: POST /generate with streaming
            if decode_slots:
                self.enable_decode_sessions(
                    slots=decode_slots,
                    prefill_chunk=decode_prefill_chunk,
                    fused_k=decode_fused_k,
                    draft_net=decode_draft_net,
                    spec_k=decode_spec_k,
                    kv_dtype=decode_kv_dtype,
                    page_len=decode_page_len)

    # ------------------------------------------------------ control API
    def deploy(self, name: str, version, net, *, feat_shape=None,
               warm: bool = True):
        """Zero-downtime hot-swap: warm the new version's bucketed jit
        caches, atomically flip traffic, drain + retire the old one."""
        return self.registry.deploy(name, version, net,
                                    feat_shape=feat_shape, warm=warm)

    def enable_decode_sessions(self, model: str = DEFAULT_MODEL, *,
                               slots: int = 4, prefill_chunk: int = 8,
                               fused_k: Optional[int] = None,
                               draft_net=None,
                               spec_k: Optional[int] = None,
                               kv_dtype: Optional[str] = None,
                               page_len: Optional[int] = None,
                               warm: bool = True):
        """Attach a DecodeSessionManager to `model`: POST /generate
        streams tokens from per-request sessions over a shared KV slot
        pool, stepped through the continuous-batching scheduler.
        `fused_k` requests a fused decode window length (None = the
        `decode_loop_policy` default; env hatches still win).
        `draft_net` wires in a speculative-decoding draft model (same
        vocab, rewind-capable) and `spec_k` its proposals-per-window;
        `kv_dtype` ("int8"/"fp8") quantizes the KV slot pools'
        cache storage; `page_len` requests a KV page length for the
        prefix cache (paged storage + radix prefix reuse — on by
        default when the model can page its KV). All defer to their
        kernel_defaults policy lattice — DL4J_TPU_SPEC_DECODE /
        DL4J_TPU_DRAFT_K / DL4J_TPU_KV_DTYPE / DL4J_TPU_PREFIX_CACHE /
        DL4J_TPU_KV_PAGE force-override."""
        if self.mode != "continuous":
            raise ValueError(
                "decode sessions need the continuous scheduler "
                f"(server mode is {self.mode!r})")
        if model in self._decode:
            raise ValueError(f"decode sessions already enabled "
                             f"for {model!r}")
        from deeplearning4j_tpu.serving.sessions import (
            DecodeSessionManager,
        )
        mgr = DecodeSessionManager(
            self.registry, self.scheduler, model, slots=slots,
            prefill_chunk=prefill_chunk, fused_k=fused_k,
            draft_net=draft_net, spec_k=spec_k, kv_dtype=kv_dtype,
            page_len=page_len, metrics=self.stats.registry, warm=warm)
        self._decode[model] = mgr
        return mgr

    def enable_slo(self, *, slos=None, interval: Optional[float] = None,
                   anomaly: bool = True):
        """Turn on the telemetry time-series sampler + SLO engine for
        this server: a background thread samples `self.stats.registry`
        every `interval` (default DL4J_TPU_SERIES_INTERVAL) seconds into
        a bounded SeriesStore, and the SLOEngine + AnomalyWatch evaluate
        on each tick — all host-side, off the request path. Surfaces:
        GET /series, GET /slo, and the degraded /healthz verdict."""
        if self._sampler is not None:
            return self._slo
        from deeplearning4j_tpu.observe.series import (
            SeriesSampler, SeriesStore,
        )
        from deeplearning4j_tpu.observe.slo import (
            AnomalyWatch, SLOEngine,
        )
        self._series_store = SeriesStore()
        self._sampler = SeriesSampler(self._series_store,
                                      registry=self.stats.registry,
                                      interval=interval)
        # queue gauges only move when /metrics renders; push them every
        # tick so the series (and the SLOs over them) stay live
        self._sampler.add_callback(self._push_queue_gauges)
        self._slo = SLOEngine(self._series_store,
                              registry=self.stats.registry, slos=slos)
        self._sampler.add_callback(self._slo.evaluate)
        if anomaly:
            self._anomaly = AnomalyWatch(self._series_store,
                                         registry=self.stats.registry)
            self._sampler.add_callback(self._anomaly.check)
        self._sampler.start()
        return self._slo

    def _push_queue_gauges(self, now=None):
        depth = self.scheduler.queue_depth() if self.scheduler else None
        cap = self.scheduler.capacity if self.scheduler else None
        self.stats.set_queue_gauges(depth, cap)

    # --------------------------------------------------------- handlers
    def _parse(self, req: dict):
        x_raw = req["ndarray"]          # KeyError → 400
        try:
            x = np.asarray(x_raw, np.float32)
        except Exception as e:
            raise HttpError(400, f"bad ndarray payload: {e}")
        if x.ndim < 2:
            raise HttpError(400, "ndarray must be [batch, features...]")
        model = req.get("model", DEFAULT_MODEL)
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                raise HttpError(400, "deadline_ms must be a number")
        return x, model, deadline_ms

    @staticmethod
    def _trace_extra(rt) -> dict:
        return {"trace_id": rt.trace_id} if rt is not None else {}

    def _output(self, req: dict):
        x, model, deadline_ms = self._parse(req)
        # the trace is born at the HTTP edge: rt is None on the
        # sampled-off fast path and every seam below only pays an
        # `is None` check
        rt = reqtrace.new_trace("http.output")
        try:
            y, version = self._output_dispatch(model, x, deadline_ms, rt)
        except HttpError as e:
            reqtrace.finish_root(rt, route="/output", model=model,
                                 status=e.status)
            if rt is not None:
                e.payload.setdefault("trace_id", rt.trace_id)
            raise
        out = {"output": np.asarray(y).tolist(), "model": model,
               "version": version}
        if rt is not None:
            reqtrace.finish_root(rt, route="/output", model=model,
                                 status=200, rows=int(x.shape[0]))
            out["trace_id"] = rt.trace_id
        return out

    def _output_dispatch(self, model, x, deadline_ms, rt):
        if self.mode == "continuous":
            try:
                fut = self.scheduler.submit(model, x, deadline_ms,
                                            trace=rt)
                y = fut.result()
                return y, getattr(fut, "version", None)
            except RequestShedError as e:
                raise HttpError(503, f"shed: {e}",
                                **reqtrace.error_extra(e))
            except DeadlineExceededError as e:
                raise HttpError(504, f"deadline exceeded: {e}",
                                **reqtrace.error_extra(e))
            except SchedulerClosedError as e:
                raise HttpError(503, f"draining: {e}")
            except KeyError:
                raise HttpError(400, f"unknown model: {model!r}")
        t0 = time.monotonic()
        try:
            entry = self.registry.acquire(model)
        except KeyError:
            raise HttpError(400, f"unknown model: {model!r}")
        self.stats.admitted(model)
        try:
            y = entry.output(x)
            version = entry.version
        except BaseException:
            self.stats.completed(model, 0.0, ok=False)
            raise
        finally:
            self.registry.release(entry)
        self.stats.completed(model, time.monotonic() - t0)
        return y, version

    def _generate(self, req: dict):
        """Stateful decode: open a session, stream its tokens. With
        "stream": true (default) the response is SSE — one `data:` frame
        per token, then a terminal done/error frame; client disconnect
        cancels the session. With "stream": false the handler blocks and
        returns the full generation as one JSON body."""
        model = req.get("model", DEFAULT_MODEL)
        mgr = self._decode.get(model)
        if mgr is None:
            raise HttpError(
                400, f"decode sessions are not enabled for {model!r}")
        prompt = req["prompt_ids"]              # KeyError → 400
        kw = {}
        for field, cast in (("max_tokens", int), ("temperature", float),
                            ("top_k", int), ("top_p", float),
                            ("greedy", bool), ("seed", int),
                            ("deadline_ms", float), ("eos_id", int)):
            if req.get(field) is not None:
                try:
                    kw[field] = cast(req[field])
                except (TypeError, ValueError):
                    raise HttpError(400, f"bad {field}: {req[field]!r}")
        rt = reqtrace.new_trace("http.generate")
        try:
            sess = mgr.open_session(prompt, trace=rt, **kw)
        except SlotPoolExhaustedError as e:
            reqtrace.finish_root(rt, route="/generate", status=503)
            raise HttpError(503, f"no free decode slot: {e}",
                            **self._trace_extra(rt))
        except SchedulerClosedError as e:
            reqtrace.finish_root(rt, route="/generate", status=503)
            raise HttpError(503, f"draining: {e}", **self._trace_extra(rt))
        except (TypeError, ValueError) as e:
            reqtrace.finish_root(rt, route="/generate", status=400)
            raise HttpError(400, str(e), **self._trace_extra(rt))
        if req.get("stream", True):
            def events():
                try:
                    first = {"session": sess.id, "model": model}
                    if rt is not None:
                        first["trace_id"] = rt.trace_id
                    yield first
                    for ev in sess.stream():
                        yield ev
                finally:
                    # client disconnect lands here as GeneratorExit
                    if not sess.done.is_set():
                        sess.cancel()
                    reqtrace.finish_root(
                        rt, route="/generate", model=model,
                        session=sess.id, tokens=len(sess.generated),
                        outcome=sess.outcome)
            return StreamResponse(events())
        try:
            tokens = sess.result()
        except DeadlineExceededError as e:
            reqtrace.finish_root(rt, route="/generate", model=model,
                                 session=sess.id, status=504)
            raise HttpError(504, f"deadline exceeded: {e}",
                            **(reqtrace.error_extra(e)
                               or self._trace_extra(rt)))
        except (RequestShedError, SchedulerClosedError) as e:
            reqtrace.finish_root(rt, route="/generate", model=model,
                                 session=sess.id, status=503)
            raise HttpError(503, str(e),
                            **(reqtrace.error_extra(e)
                               or self._trace_extra(rt)))
        out = {"session": sess.id, "model": model, "tokens": tokens,
               "outcome": sess.outcome, "ttft_ms": sess.ttft_ms}
        if rt is not None:
            reqtrace.finish_root(rt, route="/generate", model=model,
                                 session=sess.id, status=200,
                                 tokens=len(tokens),
                                 outcome=sess.outcome)
            out["trace_id"] = rt.trace_id
        return out

    def _generate_cancel(self, req: dict):
        model = req.get("model", DEFAULT_MODEL)
        mgr = self._decode.get(model)
        if mgr is None:
            raise HttpError(
                400, f"decode sessions are not enabled for {model!r}")
        sid = req["session"]                    # KeyError → 400
        return {"session": sid, "cancelled": mgr.cancel(sid)}

    def _sessions(self):
        return {"decode": {m: mgr.snapshot()
                           for m, mgr in self._decode.items()}}

    def _owned_watchdog_tags(self):
        """Owner tags of jit caches THIS server's models/sessions own —
        healthz folds watchdog trips for these only, so another
        component's churn in the same process can't degrade us."""
        tags = set()
        get = getattr(self.registry, "get", None)
        for name in (self.registry.names() if get else ()):
            try:
                entry = get(name)
            except KeyError:
                continue
            tag = getattr(getattr(getattr(entry, "runner", None),
                                  "_jit_cache", None), "owner_tag", None)
            if tag:
                tags.add(tag)
        for mgr in self._decode.values():
            tag = getattr(getattr(mgr, "_jit_cache", None),
                          "owner_tag", None)
            if tag:
                tags.add(tag)
        return tags

    def _healthz(self):
        """Degraded verdict with the reason list in the body. Degraded
        when: the admission queue passes `degraded_fraction` of
        capacity, OR the recompile watchdog tripped on one of this
        server's jit owners, OR a slot worker is crash-looping right
        now, OR any SLO is firing."""
        depth = self.scheduler.queue_depth() if self.scheduler else 0
        cap = self.scheduler.capacity if self.scheduler else None
        reasons = []
        if cap is not None and depth >= self.degraded_fraction * cap:
            reasons.append(f"admission queue saturated ({depth}/{cap})")
        from deeplearning4j_tpu.observe.watchdog import get_watchdog
        owned = self._owned_watchdog_tags()
        snap = get_watchdog().snapshot()["per_owner"] if owned else {}
        tripped = sorted(t for t, o in snap.items()
                         if o["warned"] and t in owned)
        if tripped:
            reasons.append(
                "recompile watchdog tripped: " + ", ".join(tripped))
        streak = (self.scheduler.restart_streak()
                  if self.scheduler else 0)
        if streak:
            reasons.append(
                f"slot worker crash-looping (streak {streak})")
        firing = self._slo.firing() if self._slo is not None else []
        for name in firing:
            reasons.append(f"slo firing: {name}")
        out = {"status": "degraded" if reasons else "ok",
               "reasons": reasons, "mode": self.mode,
               "queue_depth": depth, "queue_capacity": cap,
               "models": self.registry.names()}
        if self._slo is not None:
            out["slo_firing"] = firing
            if firing:
                out["slo_breaches"] = self._slo.breaches()
        return out

    def _series(self, request=None):
        """GET /series — the sampled time-series windows. Query params:
        `window` (seconds of history) and `prefix` (key filter)."""
        if self._series_store is None:
            return {"enabled": False, "series": {}}
        q = (request or {}).get("query", {})

        def _f(name):
            try:
                return float(q[name][0]) if q.get(name) else None
            except (TypeError, ValueError):
                raise HttpError(400, f"bad {name!r} query param")
        out = self._series_store.snapshot(
            window_s=_f("window"),
            prefix=(q.get("prefix") or [None])[0])
        out["enabled"] = True
        out["interval_s"] = self._sampler.interval
        out["ticks"] = self._sampler.ticks
        return out

    def _slo_route(self, request=None):
        """GET /slo — the engine's last evaluation (add `?refresh=1` to
        force one now, e.g. with a long sampler interval)."""
        if self._slo is None:
            return {"enabled": False, "slos": [], "firing": []}
        q = (request or {}).get("query", {})
        if q.get("refresh"):
            self._sampler.sample_once()
        out = dict(self._slo.snapshot())
        out["enabled"] = True
        if self._anomaly is not None:
            out["anomalies"] = list(self._anomaly.warnings)
        return out

    def _metrics(self, request=None):
        depth = self.scheduler.queue_depth() if self.scheduler else 0
        cap = self.scheduler.capacity if self.scheduler else None
        fmt = (request or {}).get("query", {}).get("format", [])
        if fmt and fmt[0].lower() == "registry":
            # the fleet scraper's format: the raw registry snapshot
            # (counters/gauges/histograms+buckets), mergeable by
            # observe.fedmon without re-deriving from the stats shape
            self.stats.set_queue_gauges(depth, cap)
            return self.stats.registry.snapshot()
        if request is not None and self._wants_prometheus(request):
            self.stats.set_queue_gauges(depth, cap)
            return TextResponse(self.stats.registry.to_prometheus(),
                                content_type=PROMETHEUS_CONTENT_TYPE)
        snap = self.stats.snapshot(queue_depth=depth, queue_capacity=cap)
        if self._decode:        # additive: only when sessions exist
            snap["decode"] = {m: mgr.snapshot()
                              for m, mgr in self._decode.items()}
        return snap

    @staticmethod
    def _wants_prometheus(request) -> bool:
        """Prometheus scrapers advertise text/plain (or openmetrics) in
        Accept; plain JSON consumers (and the pre-existing tests) send no
        Accept preference and keep the JSON snapshot."""
        fmt = request.get("query", {}).get("format", [])
        if fmt:
            return fmt[0].lower() in ("prometheus", "text")
        accept = (request.get("headers") or {}).get("Accept", "") or ""
        return "text/plain" in accept or "openmetrics" in accept

    def _devices(self):
        from deeplearning4j_tpu.observe.devicemon import get_device_monitor

        mon = get_device_monitor()
        return {"devices": mon.sample_once(), "polls": mon.polls,
                "monitor_running": mon.running}

    def _flight(self):
        from deeplearning4j_tpu.observe.flight import get_flight

        return get_flight().snapshot()

    def _flight_sub(self, suffix: str, request=None):
        """GET /flight/latest — the newest on-disk dump bundle as JSON
        (404 when this process has never dumped). Events are capped so
        the response stays bounded even with a large keep budget."""
        from deeplearning4j_tpu.observe.flight import (
            get_flight, latest_dump, read_dump,
        )

        sub = suffix.strip("/")
        if sub != "latest":
            raise HttpError(404, f"unknown flight endpoint: {sub!r}")
        path = latest_dump(get_flight().dump_dir)
        if path is None:
            raise HttpError(404, "no flight dump recorded yet")
        doc = read_dump(path)
        events = doc.get("events")
        if isinstance(events, list) and len(events) > 500:
            doc["events"] = events[-500:]
            doc["events_truncated"] = len(events) - 500
        doc["path"] = path
        return doc

    def _flight_dump(self, req: dict):
        """POST /flight/dump — force a dump now (the fleet incident
        collector asks survivors for their state at the incident)."""
        from deeplearning4j_tpu.observe.flight import get_flight

        reason = str(req.get("reason") or "requested")[:120]
        path = get_flight().dump(reason)
        return {"ok": path is not None, "path": path, "reason": reason}

    def _trace_list(self):
        store = reqtrace.get_trace_store()
        ids = store.ids()
        return {"traces": ids[-50:], "count": len(ids),
                "sample_rate": reqtrace.sample_rate()}

    def _trace(self, suffix: str, request=None):
        tid = suffix.strip("/")
        if not tid:
            return self._trace_list()
        tree = reqtrace.get_trace_store().tree(tid)
        if tree is None:
            raise HttpError(404, f"unknown trace: {tid!r}")
        return tree

    def get_routes(self):
        return {"/healthz": self._healthz, "/metrics": self._metrics,
                "/models": lambda: {"models": self.registry.summary()},
                "/devices": self._devices, "/flight": self._flight,
                "/sessions": self._sessions, "/trace": self._trace_list,
                "/series": self._series, "/slo": self._slo_route}

    def get_prefix_routes(self):
        return {"/trace/": self._trace, "/flight/": self._flight_sub}

    def post_routes(self):
        return {"/output": self._output, "/generate": self._generate,
                "/generate/cancel": self._generate_cancel,
                "/flight/dump": self._flight_dump}

    def stop(self):
        super().stop()
        # the sampler thread reads stats/scheduler state; stop it before
        # tearing those down (idempotent join)
        if self._sampler is not None:
            self._sampler.stop()
        # abort live decode sessions first — their callback chains keep
        # resubmitting into the scheduler; closing them makes the
        # scheduler/registry shutdown below drain instead of time out
        for mgr in self._decode.values():
            mgr.shutdown()
        if self.scheduler is not None:
            self.scheduler.shutdown()
        self.registry.close()


# the control-plane-flavored name; same object
ModelServer = InferenceServer
