"""REST model-inference server backed by ParallelInference.

Reference precedent: the reference embeds `ParallelInference` in user code;
this exposes it over HTTP like the nearest-neighbor server exposes VPTree:
  POST /output  {"ndarray": [[...], ...]}  → {"output": [[...], ...]}
  GET  /healthz
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.parallel.inference import InferenceMode, ParallelInference


class InferenceServer:
    def __init__(self, net, *, port: int = 9001, batched: bool = True,
                 max_batch_size: int = 64):
        self.pi = ParallelInference(
            net,
            mode=InferenceMode.BATCHED if batched else InferenceMode.INPLACE,
            max_batch_size=max_batch_size)
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None

    def start(self) -> int:
        pi = self.pi

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/output":
                    return self._json(404, {"error": "not found"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    x = np.asarray(req["ndarray"], np.float32)
                    out = pi.output(x)
                    self._json(200, {"output": np.asarray(out).tolist()})
                except Exception as e:
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_port
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.pi.shutdown()
