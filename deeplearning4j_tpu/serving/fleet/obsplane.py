"""Fleet observability plane — the router-side half of the cross-process
spine (observe/fedmon.py is the data model; this module does the I/O).

Three capabilities, all strictly PULL-based and entirely off every
replica's dispatch path (a scrape or a stitch costs a replica exactly
one HTTP GET served by its control-plane thread — never a host sync, a
lock on the decode path, or a compile; the perf gate's fedmon leg pins
the 0-sync / 0-compile budget):

1. **Trace stitching** — `stitched_trace(tid)` takes the router's own
   tree for a fleet request, and for every `prefill.hop` / `decode.hop`
   span carrying a `replica_trace` id pulls that replica's subtree via
   its existing `GET /trace/{id}` and grafts it underneath
   (reqtrace.graft_subtree), producing ONE causal waterfall across
   processes. Each graft root is stamped `boundary="process"` with the
   replica name, its pid (recovered from the trace-id scheme), and a
   clock-skew estimate from the hop's request/response wall bounds
   (NTP-style: ((t1-t0)+(t2-t3))/2). Dead replicas degrade to an
   `replica.unreachable` placeholder span — the waterfall never 500s
   because a process died; `failover` spans always mark their dead
   replica this way.

2. **Federated metrics + fleet SLOs** — `scrape_once()` runs on the
   router's poll loop: pulls every replica's registry snapshot
   (`/metrics?format=registry`), merges it through `FleetFederation`
   (restart-safe counter deltas, bucket-wise histograms, labeled
   gauges, staleness marks), records the merged view into a fleet
   SeriesStore (the scrape IS the fleet sampler) alongside the
   router's own registry, and evaluates fleet-scope burn-rate SLOs
   over that merged store. A newly-firing fleet SLO feeds the SAME
   auto-drain control loop: the worst-offending replica drains (warm
   migration included) and is undrained when the objective resolves.

3. **Incident bundles** — `trigger_incident()` (SLO breach, failover,
   deploy rollback, replica crash) collects the router's flight dump,
   stitched last-K traces, and — from every involved replica — a
   freshly-requested flight dump plus its last-K trace trees into one
   self-contained `incident-<ts>-<reason>/` directory with a
   manifest.json (tools/incident_view.py renders it). Collection runs
   on a detached thread with bounded timeouts: an incident never slows
   the stream that tripped it.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.observe import fedmon, reqtrace
from deeplearning4j_tpu.observe.flight import get_flight
from deeplearning4j_tpu.observe.series import SeriesSampler, SeriesStore
from deeplearning4j_tpu.observe.slo import SLOEngine
from deeplearning4j_tpu.serving.fleet import client

logger = logging.getLogger(__name__)

ENV_INCIDENT_DIR = "DL4J_TPU_INCIDENT_DIR"
ENV_INCIDENT_KEEP = "DL4J_TPU_INCIDENT_KEEP"
ENV_INCIDENT_MIN_S = "DL4J_TPU_INCIDENT_MIN_S"
DEFAULT_INCIDENT_KEEP = 8
DEFAULT_INCIDENT_MIN_S = 30.0
SCRAPE_TIMEOUT_S = 5.0
TRACE_LAST_K = 4

_HOP_SPANS = ("prefill.hop", "decode.hop")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FleetObsPlane:
    """Owned by a FleetRouter; duck-types against it (`replica_urls()`,
    `registry`, `auto_drain_on_slo`, `drain_replica`/`undrain_replica`,
    `_c_slo_drains`)."""

    def __init__(self, router, *, slos=None,
                 incident_dir: Optional[str] = None,
                 incident_min_interval_s: Optional[float] = None,
                 stale_after_s: Optional[float] = None,
                 trace_last_k: int = TRACE_LAST_K):
        self.router = router
        self.federation = fedmon.FleetFederation(
            stale_after_s=stale_after_s)
        self.store = SeriesStore()
        # manual ticks only (scrape_once drives it); never start()ed
        self._sampler = SeriesSampler(self.store,
                                      registry=router.registry,
                                      interval=3600.0)
        self.slo_engine = SLOEngine(
            self.store, registry=router.registry,
            slos=slos if slos is not None else
            fedmon.default_fleet_slos())
        self.incident_dir = (incident_dir
                             or os.environ.get(ENV_INCIDENT_DIR)
                             or get_flight().dump_dir)
        self.incident_min_interval_s = (
            incident_min_interval_s if incident_min_interval_s is not None
            else _env_float(ENV_INCIDENT_MIN_S, DEFAULT_INCIDENT_MIN_S))
        self.trace_last_k = max(1, int(trace_last_k))
        self._lock = threading.Lock()
        # graft: guarded-by(_lock)
        self._prev_firing: set = set()
        # fleet SLO name -> replica it auto-drained (undrain on resolve)
        # graft: guarded-by(_lock)
        self._fleet_drained: Dict[str, str] = {}
        # graft: guarded-by(_lock)
        self._last_incident_ts = 0.0
        # graft: guarded-by(_lock)
        self._incident_seq = 0
        # graft: guarded-by(_lock)
        self._threads: List[threading.Thread] = []
        # manifest paths of recent bundles (newest last)
        # graft: guarded-by(_lock)
        self.recent: deque = deque(maxlen=32)
        self.scrapes = 0

    # ---------------------------------------------------------- scraping
    def scrape_once(self, now: Optional[float] = None) -> dict:
        """One federation tick, called from the router's poll loop (or
        synchronously by tests): scrape replicas → merge → record the
        merged series → evaluate fleet SLOs → feed the drain loop.
        Never raises; a dead replica is a staleness mark, not an error."""
        now = time.time() if now is None else now
        urls = self.router.replica_urls()
        for name, url in urls.items():
            try:
                snap = client.get_json(url, "/metrics?format=registry",
                                       timeout=SCRAPE_TIMEOUT_S)
                self.federation.ingest(name, snap, now)
            except (client.ReplicaUnreachable,
                    client.ReplicaHTTPError) as e:
                logger.debug("fleet scrape of %s failed: %s", name, e)
                self.federation.mark_unreachable(name, now)
        for name, labels, kind, value in self.federation.series_points():
            self.store.record(name, labels, now, value, kind=kind)
        # the router's own counters join the same store so fleet SLOs
        # can ratio over them (failed handoffs / handoffs)
        self._sampler.sample_once(now)
        payload = self.slo_engine.evaluate(now)
        # graft: allow(GL301): single writer — scrape_once runs on the
        # poll thread only (tests call it synchronously)
        self.scrapes += 1
        self._apply_slo_transitions(payload, urls)
        return payload

    def _apply_slo_transitions(self, payload: dict, urls: dict) -> None:
        firing = set(payload.get("firing") or ())
        with self._lock:
            fired = firing - self._prev_firing
            resolved = self._prev_firing - firing
            self._prev_firing = firing
            undrain = [(n, self._fleet_drained.pop(n))
                       for n in list(self._fleet_drained)
                       if n in resolved]
        for name in fired:
            slo = next((s for s in self.slo_engine.slos
                        if s.name == name), None)
            worst = self._worst_replica(slo) if slo is not None else None
            self.trigger_incident(f"slo_breach_{name}",
                                  sorted(urls),
                                  {"slo": name, "worst_replica": worst})
            if worst is not None and \
                    getattr(self.router, "auto_drain_on_slo", False):
                logger.warning("fleet SLO %s firing: draining %s",
                               name, worst)
                self.router._c_slo_drains.inc()
                try:
                    self.router.drain_replica(
                        worst, reason=f"fleet slo: {name}")
                    with self._lock:
                        self._fleet_drained[name] = worst
                # graft: allow(GL403): replica vanished between verdict
                # and drain — the poll loop will mark it unhealthy
                except Exception:
                    logger.exception("fleet SLO drain of %s failed",
                                     worst)
        for name, replica in undrain:
            try:
                self.router.undrain_replica(replica)
            # graft: allow(GL403): best-effort lift — the operator can
            # undrain manually; state is visible in /fleet
            except Exception:
                logger.exception("fleet SLO undrain of %s failed",
                                 replica)

    def _worst_replica(self, slo) -> Optional[str]:
        """Attribute a fleet-level breach to the worst single replica so
        the drain loop has a target: highest per-replica quantile for
        value objectives over `name:pNN`, highest per-replica failure
        total for ratio objectives."""
        try:
            doc = self.federation.snapshot()
        except Exception:                     # pragma: no cover
            return None
        series = doc.get("series") or {}
        worst, worst_v = None, None
        if slo.kind == "value" and ":" in slo.series:
            base, q = slo.series.rsplit(":", 1)
            for entry in series.get(base, ()):
                rep = (entry.get("labels") or {}).get("replica")
                v = entry.get(q)
                if rep is None or not isinstance(v, (int, float)):
                    continue
                bad = worst_v is None or (v > worst_v if slo.op == ">"
                                          else v < worst_v)
                if bad:
                    worst, worst_v = rep, v
        elif slo.kind == "ratio":
            names = [lab.get("__series__", slo.series)
                     for lab in (slo.num or [{}])]
            for nm in names:
                for entry in series.get(nm, ()):
                    rep = (entry.get("labels") or {}).get("replica")
                    v = entry.get("value")
                    if rep is None or not isinstance(v, (int, float)):
                        continue
                    if worst_v is None or v > worst_v:
                        worst, worst_v = rep, v
        return worst

    # --------------------------------------------------------- stitching
    def stitched_trace(self, trace_id: str,
                       raw: bool = False) -> Optional[dict]:
        """The router's tree for `trace_id` with every hop's replica
        subtree grafted in. Fetches run with NO router lock held."""
        doc = reqtrace.get_trace_store().tree(trace_id)
        if doc is None or raw:
            return doc
        urls = self.router.replica_urls()
        grafted = [0]

        def visit(node):
            attrs = node.get("attrs") or {}
            name = node.get("name")
            if name in _HOP_SPANS and attrs.get("replica_trace"):
                self._graft_hop(node, attrs, urls, grafted)
            elif name == "failover" and attrs.get("dead"):
                self._graft_unreachable(
                    node, str(attrs["dead"]), None,
                    "replica died mid-stream (failover)", grafted)
            for c in list(node.get("children", ())):
                visit(c)

        for root in doc.get("tree", ()):
            visit(root)
        reqtrace.tree_stats(doc)
        doc["stitched"] = True
        doc["grafted_spans"] = grafted[0]
        return doc

    def _graft_hop(self, node: dict, attrs: dict, urls: dict,
                   grafted: list) -> None:
        rtid = str(attrs["replica_trace"])
        replica = attrs.get("replica")
        url = urls.get(replica)
        if url is None:
            self._graft_unreachable(node, replica, rtid,
                                    "replica no longer in the fleet",
                                    grafted)
            return
        try:
            sub = client.get_json(url, f"/trace/{rtid}",
                                  timeout=SCRAPE_TIMEOUT_S)
        except (client.ReplicaUnreachable,
                client.ReplicaHTTPError) as e:
            self._graft_unreachable(node, replica, rtid, str(e)[:200],
                                    grafted)
            return
        # clock skew from the hop's request/response wall bounds
        # (t0/t3 router clock) vs the replica roots' bounds (t1/t2):
        # offset = ((t1-t0)+(t2-t3))/2, positive = replica clock ahead
        roots = sub.get("tree") or []
        skew_s = 0.0
        t0 = node.get("ts")
        dur = node.get("dur_ms") or 0.0
        if roots and isinstance(t0, (int, float)):
            t3 = t0 + dur / 1e3
            t1 = min(r.get("ts", t0) for r in roots)
            t2 = max(r.get("ts", t0) + (r.get("dur_ms") or 0.0) / 1e3
                     for r in roots)
            skew_s = ((t1 - t0) + (t2 - t3)) / 2.0
        grafted[0] += reqtrace.graft_subtree(
            node, sub, skew_s=skew_s, replica=replica,
            pid=reqtrace.pid_of_trace_id(rtid),
            clock_skew_ms=round(skew_s * 1e3, 3))

    @staticmethod
    def _graft_unreachable(node: dict, replica, rtid, error: str,
                           grafted: list) -> None:
        ph = {"name": "replica.unreachable", "ts": node.get("ts"),
              "dur_ms": 0.0, "span_id": None,
              "parent_id": node.get("span_id"),
              "trace_id": rtid or node.get("trace_id"),
              "thread": "-",
              "attrs": {"boundary": "process", "unreachable": True,
                        "replica": replica,
                        "pid": reqtrace.pid_of_trace_id(rtid or ""),
                        "error": error}}
        node.setdefault("children", []).append(ph)
        grafted[0] += 1

    # --------------------------------------------------------- incidents
    def trigger_incident(self, reason: str, involved: List[str],
                         extra: Optional[dict] = None,
                         sync: bool = False) -> Optional[str]:
        """Rate-limited bundle collection on a detached thread (or
        inline with `sync=True`); returns the bundle dir for sync calls,
        else None. Never raises."""
        now = time.time()
        with self._lock:
            if now - self._last_incident_ts < \
                    self.incident_min_interval_s:
                return None
            self._last_incident_ts = now
            self._incident_seq += 1
            seq = self._incident_seq
        if sync:
            return self._collect(reason, list(involved), extra or {},
                                 seq)
        t = threading.Thread(
            target=self._collect,
            args=(reason, list(involved), extra or {}, seq),
            name=f"fleet-incident-{seq}", daemon=True)
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()
        return None

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Join outstanding incident collectors (tests/smoke)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return all(not t.is_alive() for t in threads)

    def _collect(self, reason: str, involved: List[str], extra: dict,
                 seq: int) -> Optional[str]:
        try:
            return self._collect_inner(reason, involved, extra, seq)
        # graft: allow(GL403): incident collection is best-effort by
        # contract — it must never take down the poll loop or a stream
        except Exception:
            logger.exception("incident collection failed (%s)", reason)
            return None

    def _collect_inner(self, reason: str, involved: List[str],
                       extra: dict, seq: int) -> str:
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:48] or "incident"
        bundle = os.path.join(
            self.incident_dir,
            f"incident-{int(time.time() * 1000)}-{os.getpid()}"
            f"-{seq:03d}-{slug}")
        os.makedirs(bundle, exist_ok=True)
        manifest: dict = {"reason": reason, "ts": round(time.time(), 3),
                          "router_pid": os.getpid(), "extra": extra,
                          "replicas": []}
        # 1. the router's own black box
        path = get_flight().dump(
            f"incident_{reason}",
            path=os.path.join(bundle, "router_flight.json"))
        manifest["router_flight"] = (os.path.basename(path)
                                     if path else None)
        # 2. stitched last-K traces (the cross-process waterfalls the
        #    flight dump alone cannot carry)
        stitched = []
        for tree in reqtrace.get_trace_store().last_trees(
                self.trace_last_k):
            try:
                stitched.append(self.stitched_trace(tree["trace_id"])
                                or tree)
            # graft: allow(GL403): a half-dead fleet still bundles —
            # fall back to the unstitched local tree
            except Exception:
                stitched.append(tree)
        with open(os.path.join(bundle, "stitched_traces.json"),
                  "w") as f:
            json.dump(stitched, f, indent=1, default=str)
        manifest["stitched_traces"] = "stitched_traces.json"
        manifest["stitched_count"] = len(stitched)
        # 3. every involved replica's dump + last-K traces
        urls = self.router.replica_urls()
        names = [n for n in involved if n in urls] or sorted(urls)
        for name in names:
            manifest["replicas"].append(
                self._collect_replica(name, urls[name], reason, bundle))
        mpath = os.path.join(bundle, "manifest.json")
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
        os.replace(tmp, mpath)
        with self._lock:
            self.recent.append(mpath)
        self.router.registry.counter("fleet_incidents_total",
                                     reason=reason).inc()
        self._prune_bundles()
        logger.warning("fleet incident bundle written: %s (%s)",
                       bundle, reason)
        return bundle

    def _collect_replica(self, name: str, url: str, reason: str,
                         bundle: str) -> dict:
        row: dict = {"name": name, "url": url, "unreachable": False,
                     "error": None, "flight": None, "traces": None}
        try:
            # ask for a fresh dump so the bundle carries the replica's
            # state AT the incident, not whenever it last crashed;
            # fall back to whatever artifact already exists
            try:
                client.post_json(url, "/flight/dump",
                                 {"reason": f"incident_{reason}"},
                                 timeout=SCRAPE_TIMEOUT_S)
            # graft: allow(GL403): older replicas lack POST /flight/dump
            # — the /flight/latest fallback below still applies
            except client.ReplicaHTTPError:
                pass
            try:
                dump = client.get_json(url, "/flight/latest",
                                       timeout=SCRAPE_TIMEOUT_S)
                fname = f"replica_{name}_flight.json"
                with open(os.path.join(bundle, fname), "w") as f:
                    json.dump(dump, f, indent=1, default=str)
                row["flight"] = fname
            except client.ReplicaHTTPError as e:
                row["error"] = f"no flight dump: {e}"
            listing = client.get_json(url, "/trace",
                                      timeout=SCRAPE_TIMEOUT_S)
            trees = []
            for tid in (listing.get("traces") or [])[-self.trace_last_k:]:
                try:
                    trees.append(client.get_json(
                        url, f"/trace/{tid}",
                        timeout=SCRAPE_TIMEOUT_S))
                # graft: allow(GL403): trace evicted between list and
                # fetch — bundle the ones that survive
                except client.ReplicaHTTPError:
                    pass
            if trees:
                fname = f"replica_{name}_traces.json"
                with open(os.path.join(bundle, fname), "w") as f:
                    json.dump(trees, f, indent=1, default=str)
                row["traces"] = fname
            row["trace_count"] = len(trees)
        except (client.ReplicaUnreachable, OSError) as e:
            row["unreachable"] = True
            row["error"] = str(e)[:200]
        return row

    def _prune_bundles(self) -> None:
        """Keep the newest DL4J_TPU_INCIDENT_KEEP incident-* dirs."""
        try:
            keep = int(os.environ.get(ENV_INCIDENT_KEEP,
                                      str(DEFAULT_INCIDENT_KEEP)))
        except ValueError:
            keep = DEFAULT_INCIDENT_KEEP
        try:
            dirs = sorted(
                d for d in os.listdir(self.incident_dir)
                if d.startswith("incident-")
                and os.path.isdir(os.path.join(self.incident_dir, d)))
            for d in dirs[:-keep] if keep > 0 else dirs:
                shutil.rmtree(os.path.join(self.incident_dir, d),
                              ignore_errors=True)
        # graft: allow(GL403): hygiene only — a failed prune must not
        # fail the incident that triggered it
        except OSError:
            pass

    # ----------------------------------------------------------- payload
    def metrics_payload(self, now: Optional[float] = None) -> dict:
        """The `GET /fleet/metrics` body: the merged federation view,
        scrape health, and the fleet SLO verdicts."""
        doc = self.federation.snapshot(now)
        doc["scrapes"] = self.scrapes
        doc["slo"] = self.slo_engine.snapshot()
        with self._lock:
            doc["incidents"] = list(self.recent)
            doc["fleet_drained"] = dict(self._fleet_drained)
        return doc
