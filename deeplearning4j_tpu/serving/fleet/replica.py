"""ReplicaServer — one fleet member: an InferenceServer plus the
`/fleet/*` control surface the router drives.

A replica declares a ROLE at launch:

  prefill — admits prefill-only sessions (`POST /fleet/prefill`): the
            prompt stem runs through chunked prefill into the paged
            pool, the pages are indexed in the radix, and the warm
            stem is exported as a handoff payload. No decode windows.
  decode  — imports handed-off pages (`POST /fleet/kv/import`) so the
            very next `/generate` admission matches the whole stem and
            goes straight to the decode window.
  mixed   — both (the default; a one-replica fleet is just a server).

The role is ROUTING metadata: every replica carries the full machinery
and the router chooses what to send where. Draining is advisory the
same way — the router stops placing new sessions here, and the replica
backs it up by refusing new `/generate` admissions with 503 while
in-flight streams run to completion (drain is a migration, never a
drop).

Coordinated hot-swap: `POST /fleet/deploy` ships a declarative model
SPEC (not weights — replicas rebuild deterministically via a
registered builder, the same discipline as the bench/replica-main
models), and the reply distinguishes a clean flip from a deploy
watchdog trip (`DeployRolledBackError` → `rolled_back: true`) so the
router can roll the rest of the fleet back to the previous spec.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

from deeplearning4j_tpu.observe import reqtrace
from deeplearning4j_tpu.serving.http_base import HttpError
from deeplearning4j_tpu.serving.inference_server import (
    DEFAULT_MODEL, InferenceServer,
)
from deeplearning4j_tpu.serving.kv_pool import (
    IncompatibleSessionSwapError, SlotPoolExhaustedError,
)
from deeplearning4j_tpu.serving.registry import DeployRolledBackError
from deeplearning4j_tpu.serving.fleet import handoff

ROLES = ("prefill", "decode", "mixed")

# name -> callable(spec dict) -> net. Replica processes and tests
# register builders at startup; a fleet deploy ships `{"kind": name,
# ...params}` and every replica rebuilds the same net deterministically
# (seeded init), which is what makes cross-replica greedy decode
# bit-exact without ever moving weight bytes over the wire.
_MODEL_BUILDERS: Dict[str, Callable[[dict], object]] = {}


def register_model_builder(kind: str,
                           fn: Callable[[dict], object]) -> None:
    _MODEL_BUILDERS[kind] = fn


def build_from_spec(spec: dict):
    kind = spec.get("kind")
    fn = _MODEL_BUILDERS.get(kind)
    if fn is None:
        raise ValueError(
            f"no model builder registered for kind {kind!r} "
            f"(have {sorted(_MODEL_BUILDERS)})")
    return fn(spec)


class ReplicaServer(InferenceServer):
    """InferenceServer + fleet role, drain flag, KV handoff endpoints,
    and spec-driven coordinated deploy."""

    def __init__(self, *args, role: str = "mixed",
                 replica_name: str = "replica", **kw):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        super().__init__(*args, **kw)
        self.role = role
        self.replica_name = replica_name
        self.draining = False

    # ----------------------------------------------------------- helpers
    def _mgr(self, model: str):
        mgr = self._decode.get(model)
        if mgr is None:
            raise HttpError(
                400, f"decode sessions are not enabled for {model!r}")
        return mgr

    def _paged_mgr(self, model: str):
        mgr = self._mgr(model)
        if not getattr(mgr, "prefix_enabled", False):
            raise HttpError(
                400, f"model {model!r} has no paged prefix cache — KV "
                f"handoff needs page_len and the radix index")
        return mgr

    @staticmethod
    def _prompt(req: dict, field: str = "prompt_ids") -> np.ndarray:
        try:
            prompt = np.asarray(req[field], dtype=np.int64).reshape(-1)
        except KeyError:
            raise
        except Exception as e:
            raise HttpError(400, f"bad {field}: {e}")
        if prompt.size < 1:
            raise HttpError(400, f"{field} must be non-empty")
        return prompt

    # ------------------------------------------------------ fleet routes
    def _fleet_info(self):
        decode = {}
        for model, mgr in self._decode.items():
            d = {"slots": mgr.pool.slots,
                 "slots_in_use": mgr.pool.in_use()}
            if getattr(mgr, "prefix_enabled", False):
                with mgr.pool.lock():
                    d["prefix"] = mgr.prefix_cache.stats()
                d["kv"] = mgr.pool.describe()
            decode[model] = d
        return {"name": self.replica_name, "role": self.role,
                "draining": self.draining,
                "models": self.registry.names(),
                "decode": decode}

    def _fleet_drain(self, req: dict):
        self.draining = bool(req.get("draining", True))
        return {"name": self.replica_name, "draining": self.draining}

    def _fleet_prefill(self, req: dict):
        """Run a prefill-only session and return the warm stem as a
        handoff payload — the prefill half of a disaggregated request."""
        model = req.get("model", DEFAULT_MODEL)
        mgr = self._paged_mgr(model)
        prompt = self._prompt(req)
        rt = reqtrace.new_trace("fleet.prefill")
        t0 = time.monotonic()
        try:
            sess = mgr.open_prefill(
                prompt, deadline_ms=req.get("deadline_ms"),
                alloc_timeout_s=float(req.get("alloc_timeout_s", 0.0)),
                trace=rt)
        except SlotPoolExhaustedError as e:
            reqtrace.finish_root(rt, route="/fleet/prefill", status=503)
            raise HttpError(503, f"no free prefill slot: {e}")
        except (TypeError, ValueError) as e:
            reqtrace.finish_root(rt, route="/fleet/prefill", status=400)
            raise HttpError(400, str(e))
        try:
            sess.result(timeout=60.0)
        except BaseException as e:
            reqtrace.finish_root(rt, route="/fleet/prefill", status=500)
            raise HttpError(500, f"prefill failed: {e}")
        payload = handoff.export_prefix(mgr.pool, mgr.prefix_cache,
                                        prompt[:-1], model=model)
        out = {"session": sess.id, "model": model,
               "replica": self.replica_name,
               "prefill_ms": (time.monotonic() - t0) * 1000.0,
               "payload": payload}
        if rt is not None:
            reqtrace.finish_root(
                rt, route="/fleet/prefill", model=model,
                prompt_len=int(prompt.size),
                cached_len=0 if payload is None
                else payload["cached_len"])
            out["trace_id"] = rt.trace_id
        return out

    def _fleet_kv_export(self, req: dict):
        """Serialize the longest cached prefix of `tokens` (drain
        migration: the router pulls a session's warm stem out of a
        draining replica)."""
        model = req.get("model", DEFAULT_MODEL)
        mgr = self._paged_mgr(model)
        tokens = self._prompt(req, "tokens")
        payload = handoff.export_prefix(mgr.pool, mgr.prefix_cache,
                                        tokens, model=model)
        return {"model": model, "replica": self.replica_name,
                "payload": payload}

    def _fleet_kv_import(self, req: dict):
        model = req.get("model", DEFAULT_MODEL)
        mgr = self._paged_mgr(model)
        payload = req.get("payload")
        if not isinstance(payload, dict):
            raise HttpError(400, "missing handoff payload")
        try:
            cached_len = handoff.install_prefix(
                mgr.pool, mgr.prefix_cache, payload)
        except handoff.HandoffError as e:
            raise HttpError(400, str(e))
        except SlotPoolExhaustedError as e:
            raise HttpError(503, f"no free pages for import: {e}")
        return {"model": model, "replica": self.replica_name,
                "cached_len": cached_len,
                "bytes": handoff.payload_bytes(payload)}

    def _fleet_deploy(self, req: dict):
        """Deploy one named target from a declarative spec. Never raises
        for a deploy-shaped failure — the router needs the structured
        verdict (`rolled_back` / `incompatible`) to coordinate the
        fleet-wide rollback."""
        name = req.get("name", DEFAULT_MODEL)
        version = req.get("version")
        spec = req.get("spec")
        if version is None or not isinstance(spec, dict):
            raise HttpError(400, "deploy needs {name, version, spec}")
        try:
            net = build_from_spec(spec)
        except Exception as e:
            raise HttpError(400, f"bad model spec: {e}")
        try:
            self.registry.deploy(name, version, net,
                                 warm=bool(req.get("warm", True)))
        except DeployRolledBackError as e:
            return {"ok": False, "rolled_back": True,
                    "replica": self.replica_name, "name": name,
                    "error": str(e)}
        except IncompatibleSessionSwapError as e:
            return {"ok": False, "rolled_back": True,
                    "incompatible": True,
                    "replica": self.replica_name, "name": name,
                    "error": str(e)}
        return {"ok": True, "replica": self.replica_name,
                "name": name, "version": version}

    # ----------------------------------------------- admission override
    def _generate(self, req: dict):
        if self.draining and not req.get("_migration", False):
            # belt-and-braces behind the router's own bookkeeping: a
            # draining replica takes no NEW sessions (503 → the router
            # places elsewhere) while live streams run to completion
            raise HttpError(503,
                            f"replica {self.replica_name} is draining")
        return super()._generate(req)

    def get_routes(self):
        routes = dict(super().get_routes())
        routes["/fleet/info"] = self._fleet_info
        return routes

    def post_routes(self):
        routes = dict(super().post_routes())
        routes.update({
            "/fleet/prefill": self._fleet_prefill,
            "/fleet/kv/export": self._fleet_kv_export,
            "/fleet/kv/import": self._fleet_kv_import,
            "/fleet/drain": self._fleet_drain,
            "/fleet/deploy": self._fleet_deploy,
        })
        return routes
