"""KV page handoff: serialize a replica's warm prefix pages, install
them into another replica's paged pool — the mechanism that makes
prefill and decode separable roles.

Wire format (v1) mirrors the sharded-checkpoint manifest discipline:
every page is a dict of leaf entries keyed by the carry-tree leaf path
("layer2_transformerencoderblock/cache_k"), each entry carrying
`{shape, dtype, data}` with the raw page bytes base64-encoded AT THE
STORED DTYPE. int8/fp8 pages therefore ship as quantized bytes plus
their in-page fp32 scale rows (`scale_k`/`scale_v` are leaves like any
other) — a handoff never dequantizes, and the importer's
`import_page_locked` refuses any dtype that doesn't match its pool
bit-for-bit. Because quantization scales live per-(token, kv-head)
inside the page, the imported page is bit-exact: the decode replica
reads the very scales the prefill replica wrote.

Export and install both run under the donor/recipient pool lock as ONE
critical section each — the same serialization point as admission and
decode windows, so a handoff can never observe (or corrupt) a
half-written page. The device readback in export is a host sync by
nature; it lives on the handoff path only, never inside any replica's
decode window (the PERF_NOTES fleet contract).
"""

from __future__ import annotations

import base64
from typing import Optional

import numpy as np

try:                        # registers fp8 dtype names with numpy;
    import ml_dtypes        # ships with jax — never a new dependency
    del ml_dtypes           # noqa: F821
# graft: allow(GL403): optional dtype registration — without ml_dtypes
# fp8 handoffs fail loudly at np.dtype() lookup, fp32/int8 still work
except ImportError:         # pragma: no cover - jax always bundles it
    pass

FORMAT = "kv-handoff-v1"


class HandoffError(ValueError):
    """A handoff payload is malformed or incompatible with the
    recipient pool (dtype/page-geometry mismatch, unknown format)."""


def _leaves_to_wire(leaves: dict) -> dict:
    out = {}
    for key, arr in leaves.items():
        a = np.ascontiguousarray(arr)
        out[key] = {"shape": list(a.shape), "dtype": str(a.dtype),
                    "data": base64.b64encode(a.tobytes()).decode("ascii")}
    return out


def _wire_to_leaves(entry: dict) -> dict:
    out = {}
    for key, spec in entry.items():
        try:
            dt = np.dtype(spec["dtype"])
        except TypeError as e:
            raise HandoffError(
                f"leaf {key}: unknown dtype {spec['dtype']!r}") from e
        raw = base64.b64decode(spec["data"])
        a = np.frombuffer(raw, dtype=dt).reshape(spec["shape"])
        out[key] = a
    return out


def payload_bytes(payload: dict) -> int:
    """Decoded KV bytes a payload carries (metrics, not wire size)."""
    n = 0
    for page in payload.get("pages", []):
        for spec in page.values():
            n += (len(spec["data"]) * 3) // 4
    return n


def export_prefix(pool, cache, tokens, *, model: str = "") -> Optional[dict]:
    """Serialize the longest cached prefix of `tokens` from this
    replica's radix index. Returns the handoff payload, or None when
    nothing is cached. One pool-lock critical section: the match, the
    page readbacks, and the LRU refresh are atomic w.r.t. admission,
    eviction, and decode windows, so every exported page is consistent
    (full pages are immutable by construction; a partial page's
    content below its recorded token count was finalized by the
    donor's prefill)."""
    toks = [int(t) for t in tokens]
    with pool.lock():
        cached_len, full_pages, partial = cache.match(toks)
        if cached_len <= 0:
            return None
        pages = list(full_pages)
        partial_tokens = 0
        if partial is not None:
            pages.append(partial[0])
            partial_tokens = int(partial[1])
        wire_pages = [_leaves_to_wire(pool.export_page_locked(p))
                      for p in pages]
    return {"format": FORMAT,
            "model": model or pool.model,
            "kv_dtype": pool.kv_dtype,
            "page_len": pool.page_len,
            "cached_len": int(cached_len),
            "tokens": toks[:cached_len],
            "full_pages": len(full_pages),
            "partial_tokens": partial_tokens,
            "pages": wire_pages}


def install_prefix(pool, cache, payload: dict) -> int:
    """Install a handoff payload into this replica's pool and index it
    in the radix so the next admission's `match()` finds the warm stem.
    Returns the cached token length now resident. The recipient takes
    ownership page-by-page: fresh pages are allocated (evicting cold
    cache-only chains first if the free list is short), written with
    the dtype-preserving `import_page_locked` program, adopted by the
    radix insert, and the importer's own transient references dropped —
    a page the index declined (its chunk was already cached) returns
    straight to the free list, so a duplicate handoff leaks nothing."""
    if payload.get("format") != FORMAT:
        raise HandoffError(
            f"unknown handoff format {payload.get('format')!r}")
    if int(payload["page_len"]) != int(pool.page_len or 0):
        raise HandoffError(
            f"page_len mismatch: payload {payload['page_len']}, "
            f"pool {pool.page_len}")
    if payload["kv_dtype"] != pool.kv_dtype:
        raise HandoffError(
            f"kv_dtype mismatch: payload {payload['kv_dtype']!r}, pool "
            f"{pool.kv_dtype!r} — quantized bytes only install into an "
            f"identical-dtype pool (no dequant round-trip)")
    tokens = [int(t) for t in payload["tokens"]]
    cached_len = int(payload["cached_len"])
    if len(tokens) != cached_len:
        raise HandoffError(
            f"payload carries {len(tokens)} tokens for cached_len "
            f"{cached_len}")
    Lp = int(payload["page_len"])
    n_full = int(payload["full_pages"])
    n_partial = 1 if int(payload["partial_tokens"]) else 0
    want = n_full * Lp + int(payload["partial_tokens"])
    if want != cached_len or len(payload["pages"]) != n_full + n_partial:
        raise HandoffError(
            f"page accounting does not cover the tokens: {n_full} full "
            f"+ {payload['partial_tokens']} partial vs cached_len "
            f"{cached_len} ({len(payload['pages'])} pages shipped)")
    leaves = [_wire_to_leaves(p) for p in payload["pages"]]
    n = len(leaves)
    with pool.lock():
        short = n - pool.pages_free_locked()
        if short > 0:
            cache.evict(short)
        fresh = pool.page_alloc_locked(n)   # raises when still short
        try:
            for page, lv in zip(fresh, leaves):
                pool.import_page_locked(page, lv)
            cache.insert(tokens, fresh)
        finally:
            # the index holds its own references now; ours were only
            # for the install. Unadopted pages drop to refcount 0 here.
            for p in fresh:
                pool.page_unref_locked(p)
    return cached_len
