"""Minimal stdlib HTTP client for fleet-internal hops (router →
replica, launcher → replica). JSON request/response plus an SSE frame
iterator for proxied `/generate` streams. No third-party deps, no
retries — failover POLICY lives in the router; this module only makes
one attempt observable (every failure surfaces as ReplicaUnreachable
or ReplicaHTTPError with enough context to reroute)."""

from __future__ import annotations

import http.client
import json
import socket
from typing import Iterator, Optional, Tuple
from urllib.parse import urlsplit


class ReplicaUnreachable(ConnectionError):
    """The replica did not produce a (complete) HTTP response — connect
    refused, timeout, or the connection died mid-stream. The router
    treats this as 'replica down': reroute / failover."""


class ReplicaHTTPError(RuntimeError):
    """The replica answered with a non-2xx status (it is ALIVE — this
    is a structured refusal, e.g. 503 draining, not a crash)."""

    def __init__(self, status: int, body: dict):
        super().__init__(f"HTTP {status}: {body.get('error', body)}")
        self.status = status
        self.body = body


def _split(url: str) -> Tuple[str, int]:
    u = urlsplit(url if "//" in url else f"http://{url}")
    return u.hostname or "127.0.0.1", int(u.port or 80)


def _request(url: str, method: str, path: str, body: Optional[dict],
             timeout: float) -> http.client.HTTPResponse:
    host, port = _split(url)
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body).encode()
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
    except (OSError, socket.timeout, http.client.HTTPException) as e:
        conn.close()
        raise ReplicaUnreachable(f"{method} {url}{path}: {e}") from e
    resp._fleet_conn = conn     # keep the socket alive for streaming
    return resp


def _finish_json(resp) -> dict:
    try:
        raw = resp.read()
    except (OSError, http.client.HTTPException) as e:
        raise ReplicaUnreachable(f"truncated response: {e}") from e
    finally:
        resp._fleet_conn.close()
    try:
        body = json.loads(raw.decode() or "{}")
    except ValueError:
        body = {"error": raw.decode(errors="replace")[:200]}
    if resp.status >= 400:
        raise ReplicaHTTPError(resp.status, body)
    return body


def post_json(url: str, path: str, body: dict,
              timeout: float = 30.0) -> dict:
    return _finish_json(_request(url, "POST", path, body, timeout))


def get_json(url: str, path: str, timeout: float = 10.0) -> dict:
    return _finish_json(_request(url, "GET", path, None, timeout))


def sse_events(url: str, path: str, body: dict,
               timeout: float = 60.0) -> Iterator[dict]:
    """POST and yield each SSE `data:` frame as a parsed dict. A
    connection that dies before a terminal done/error frame raises
    ReplicaUnreachable — the caller decides whether to fail over."""
    resp = _request(url, "POST", path, body, timeout)
    if resp.status >= 400:
        yield _finish_json(resp)    # raises ReplicaHTTPError
        return
    terminal = False
    try:
        for line in resp:
            line = line.strip()
            if not line.startswith(b"data:"):
                continue
            try:
                ev = json.loads(line[5:].decode())
            except ValueError:
                continue
            yield ev
            if "done" in ev or "error" in ev:
                terminal = True
                return
        if not terminal:
            raise ReplicaUnreachable(
                f"stream from {url}{path} ended without a terminal "
                f"frame")
    except (OSError, socket.timeout, http.client.HTTPException) as e:
        raise ReplicaUnreachable(
            f"stream from {url}{path} died mid-flight: {e}") from e
    finally:
        resp._fleet_conn.close()
