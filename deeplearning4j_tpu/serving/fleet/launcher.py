"""Spawn and supervise replica PROCESSES (bench, smoke, chaos). Each
replica is a fresh interpreter running `replica_main` with a JSON
config; the launcher waits for the `FLEET_REPLICA_READY port=...`
rendezvous line and hands back a ReplicaProcess whose pid the chaos
harness's ReplicaKill can target. Stdout/stderr stream to a log file
so a dead replica leaves evidence."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional


class ReplicaLaunchError(RuntimeError):
    """The replica process died or never reported ready in time."""


class ReplicaProcess:
    """Handle on one spawned replica: name/role/url for the router,
    pid for the chaos harness, terminate() for clean teardown."""

    def __init__(self, name: str, role: str, port: int,
                 proc: subprocess.Popen, log_path: str):
        self.name = name
        self.role = role
        self.port = port
        self.proc = proc
        self.log_path = log_path

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def handle(self):
        """Router-side record for this process."""
        from deeplearning4j_tpu.serving.fleet.router import ReplicaHandle
        return ReplicaHandle(self.name, self.url, self.role)

    def terminate(self, timeout: float = 10.0) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout)

    def tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return ""


def launch_replica(config: dict, *, timeout_s: float = 120.0,
                   env: Optional[dict] = None,
                   log_dir: Optional[str] = None) -> ReplicaProcess:
    """Start one replica process from a declarative config and block
    until its HTTP server is up. The child inherits this interpreter
    (no install assumptions) and is pinned to the CPU platform unless
    FLEET_REPLICA_PLATFORM overrides."""
    name = config.get("name", "replica")
    log_dir = log_dir or tempfile.mkdtemp(prefix="fleet_")
    log_path = os.path.join(log_dir, f"{name}.log")
    child_env = dict(os.environ)
    child_env.update(env or {})
    child_env["FLEET_REPLICA_CONFIG"] = json.dumps(config)
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "deeplearning4j_tpu.serving.fleet.replica_main"],
        stdout=subprocess.PIPE, stderr=log, env=child_env, text=True)
    deadline = time.monotonic() + timeout_s
    port = None
    try:
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    break
                continue
            line = line.strip()
            if line.startswith("FLEET_REPLICA_READY"):
                port = int(line.split("port=", 1)[1])
                break
    finally:
        log.close()
    if port is None:
        rc = proc.poll()
        try:
            with open(log_path, "r", errors="replace") as f:
                tail = "".join(f.readlines()[-20:])
        except OSError:
            tail = ""
        proc.kill()
        raise ReplicaLaunchError(
            f"replica {name!r} never became ready "
            f"(exit={rc}); log tail:\n{tail}")
    return ReplicaProcess(name, config.get("role", "mixed"), port,
                          proc, log_path)
