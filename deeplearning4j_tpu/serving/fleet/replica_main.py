"""Replica process entry point:

    python -m deeplearning4j_tpu.serving.fleet.replica_main \
        --config '{"name": "r0", "role": "prefill", ...}'

Each replica is its own interpreter with its own JAX runtime/mesh —
the process boundary IS the fleet's isolation unit (a replica kill in
the chaos suite takes down one mesh, never the fleet). The config is
declarative; the model is rebuilt from its spec with seeded init, so
every replica of the same spec holds bit-identical weights without
weight bytes ever crossing the wire.

Prints exactly one `FLEET_REPLICA_READY port=<p>` line on stdout once
the HTTP server is listening (the launcher's rendezvous), then blocks
until SIGTERM/SIGINT.

Config keys (all optional but `model`):
  name, role            — replica identity + fleet role
  port                  — 0 (default) = ephemeral
  model                 — builder spec, e.g. {"kind": "bench_lm",
                          "seed": 0, "vocab": 32, "blocks": 1}
  decode_slots          — KV slots (default 4)
  prefill_chunk, fused_k, kv_dtype, page_len
                        — forwarded to enable_decode_sessions
  slo                   — {"interval": s, "objectives": [SLO kwargs]}
                          turns on the series sampler + SLO engine
                          (the router's drain signal)
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading


def build_bench_lm(spec: dict):
    """The fleet bench/test model: a tiny seeded transformer LM with a
    NON-rolling uniform cache, which is what makes it pageable
    (`prefix_cache_capable`) and therefore handoff-capable. Mirrors
    the bench.py spec-pair geometry; `seed` varies the weights for
    hot-swap legs."""
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.attention import (
        PositionEmbeddingLayer, TransformerEncoderBlock,
    )
    from deeplearning4j_tpu.nn.layers.feedforward import (
        EmbeddingSequenceLayer,
    )
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.optim.updaters import Adam

    V = int(spec.get("vocab", 32))
    chunk = int(spec.get("chunk", 8))
    max_cache = int(spec.get("max_cache", 128))
    layers = [EmbeddingSequenceLayer(n_in=V, n_out=32),
              PositionEmbeddingLayer(max_length=256)]
    for _ in range(int(spec.get("blocks", 1))):
        layers.append(TransformerEncoderBlock(
            num_heads=4, causal=True, window=32,
            rolling_cache=False, max_cache=max_cache))
    layers.append(RnnOutputLayer(n_out=V, activation="softmax"))
    conf = (NeuralNetConfiguration.builder()
            .seed(int(spec.get("seed", 0)))
            .updater(Adam(1e-3)).activation("identity")
            .list(*layers)
            .set_input_type(InputType.recurrent(1, chunk)).build())
    return MultiLayerNetwork(conf).init()


def make_server(config: dict):
    """Build a ReplicaServer from a declarative config (shared by the
    process entry below and in-process tests)."""
    from deeplearning4j_tpu.serving.fleet.replica import (
        ReplicaServer, build_from_spec, register_model_builder,
    )
    register_model_builder("bench_lm", build_bench_lm)
    net = build_from_spec(config["model"])
    slo_cfg = config.get("slo") or {}
    objectives = None
    if slo_cfg.get("objectives"):
        from deeplearning4j_tpu.observe.slo import SLO
        objectives = [SLO(kw.pop("name"), **kw)
                      for kw in (dict(o) for o in slo_cfg["objectives"])]
    srv = ReplicaServer(
        net,
        port=int(config.get("port", 0)),
        role=config.get("role", "mixed"),
        replica_name=config.get("name", "replica"),
        decode_slots=int(config.get("decode_slots", 4)),
        decode_prefill_chunk=int(config.get("prefill_chunk", 8)),
        decode_fused_k=config.get("fused_k"),
        decode_kv_dtype=config.get("kv_dtype"),
        decode_page_len=config.get("page_len"),
        max_batch_size=int(config.get("max_batch_size", 8)),
        queue_capacity=int(config.get("queue_capacity", 64)),
        slo=bool(slo_cfg),
        slo_objectives=objectives,
        series_interval=slo_cfg.get("interval"))
    return srv


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    raw = os.environ.get("FLEET_REPLICA_CONFIG", "{}")
    if "--config" in argv:
        raw = argv[argv.index("--config") + 1]
    config = json.loads(raw)
    # the sitecustomize pins "axon,cpu"; a fleet replica on a dev box
    # must come up on CPU unless the launcher says otherwise
    if not os.environ.get("FLEET_REPLICA_PLATFORM"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    srv = make_server(config)
    port = srv.start()
    print(f"FLEET_REPLICA_READY port={port}", flush=True)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
