"""Serving fleet: a router tier over N InferenceServer replicas.

The single-replica stack (scheduler -> sessions -> paged KVSlotPool ->
radix prefix cache) is fast but is one process on one mesh; this
package turns it into a horizontally scalable tier:

- `handoff`   — dtype-aware KV page serialization (quantized bytes +
                in-page scale rows, never dequantized) between the
                paged pools of two replicas, wire format mirroring the
                sharded-checkpoint leaf entries.
- `replica`   — ReplicaServer: an InferenceServer plus the /fleet/*
                control surface (role, prefill-only admission, KV
                export/import, drain, coordinated deploy).
- `router`    — FleetRouter: the HTTP front door. Disaggregated
                prefill->decode scheduling, sticky + prefix-overlap +
                load-aware placement, SLO-driven drain/reroute,
                mid-stream failover, fleet-wide hot-swap with rollback.
- `launcher`  — spawn replica processes (distinct interpreters, their
                own meshes) for benches, smoke tests, and chaos runs.
"""

from deeplearning4j_tpu.serving.fleet.handoff import (     # noqa: F401
    HandoffError, export_prefix, install_prefix, payload_bytes)
from deeplearning4j_tpu.serving.fleet.replica import (     # noqa: F401
    ReplicaServer)
from deeplearning4j_tpu.serving.fleet.router import (      # noqa: F401
    FleetRouter, ReplicaHandle)
from deeplearning4j_tpu.serving.fleet.launcher import (    # noqa: F401
    ReplicaProcess, launch_replica)
