"""FleetRouter — the HTTP front door over N replicas.

One request's life, disaggregated: the router picks a DECODE home by
sticky session id, prefix-overlap hints, and load; if a PREFILL-role
replica exists and the decode home looks cold for this prompt, the
stem is prefilled there, the warm pages ship over the dtype-aware
handoff path (quantized bytes + scale rows, never dequantized), and
the decode replica's next admission matches the whole stem — its
`/generate` goes straight to the fused decode window. The router then
proxies the SSE stream, re-numbering tokens so a mid-stream replica
death is invisible to the client: the stream resumes on another
replica (warm KV if a handoff/export survives, re-prefill otherwise)
and greedy output is bit-identical to an uninterrupted run.

Control loop: a poller hits every replica's `/healthz`; a replica
whose burn-rate SLO fires (PR 11) — or that stops answering — is
DRAINED: no new placements, live sessions' warm stems are exported
through `/fleet/kv/export` and installed into healthy replicas, and
the sticky map repoints. Coordinated hot-swap fans a declarative spec
out to every replica and rolls every already-flipped replica back if
any replica's deploy watchdog trips.

Concurrency contract (the GL701–704 lockset pass audits this file):
the replica table, session→replica map, token history, prefix hints,
and in-flight handoff set are all `guarded-by(_lock)`; NO network call
ever happens under `_lock` (GL703) — every route snapshots state under
the lock, talks HTTP unlocked, then re-takes the lock to write back.

Traces: each request is ONE causal tree rooted at the router
(`fleet.generate` → `route` / `prefill.hop` / `handoff` /
`decode.hop` / `failover` spans), with the replicas' own trace ids
attached to the hop spans — cross-process correlation without a
cross-process collector.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.observe import MetricsRegistry, reqtrace
from deeplearning4j_tpu.observe.registry import PROMETHEUS_CONTENT_TYPE
from deeplearning4j_tpu.serving.http_base import (
    HttpError, JsonHttpServer, StreamResponse, TextResponse,
)
from deeplearning4j_tpu.serving.fleet import client
from deeplearning4j_tpu.serving.fleet.handoff import payload_bytes

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_MODEL = "default"


class NoReplicaAvailableError(RuntimeError):
    """Every candidate replica is down, draining, or excluded."""


class ReplicaHandle:
    """Router-side record of one replica. Mutable fields are owned by
    the router and guarded by the router lock; the object itself never
    does I/O."""

    __slots__ = ("name", "url", "role", "draining", "healthy",
                 "fail_streak", "inflight", "slo_drained", "last_info")

    def __init__(self, name: str, url: str, role: str = "mixed"):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"bad replica role {role!r}")
        self.name = name
        self.url = url
        self.role = role
        self.draining = False
        self.healthy = True
        self.fail_streak = 0
        self.inflight = 0
        self.slo_drained = False
        self.last_info: Optional[dict] = None

    def describe(self) -> dict:
        return {"name": self.name, "url": self.url, "role": self.role,
                "draining": self.draining, "healthy": self.healthy,
                "fail_streak": self.fail_streak,
                "inflight": self.inflight,
                "slo_drained": self.slo_drained}


class FleetRouter(JsonHttpServer):
    """HTTP front door: placement, disaggregated prefill→decode
    handoff, mid-stream failover, drain migration, SLO-driven control,
    and fleet-coordinated hot-swap."""

    MAX_FAILOVERS = 2           # per stream, on top of the first home
    HINTS_PER_REPLICA = 256     # recent stems kept for overlap scoring
    SESSION_HISTORY = 4096      # fleet sessions kept for migration

    def __init__(self, replicas=(), *, port: int = 0,
                 poll_interval: Optional[float] = 1.0,
                 auto_drain_on_slo: bool = True,
                 disaggregate: bool = True,
                 handoff_min_tokens: int = 2,
                 unhealthy_after: int = 2,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(port=port)
        self._lock = threading.Lock()
        # graft: guarded-by(_lock)
        self._replicas: Dict[str, ReplicaHandle] = {}
        # fleet session id -> replica name (sticky placement)
        # graft: guarded-by(_lock)
        self._sessions: Dict[str, str] = {}
        # fleet session id -> full token history (prompt + generated),
        # the export key for drain migration; bounded FIFO
        # graft: guarded-by(_lock)
        self._history: "dict[str, list]" = {}
        # graft: guarded-by(_lock)
        self._history_order: "deque[str]" = deque()
        # replica name -> recent prompt stems (router-side overlap
        # hints against that replica's radix index)
        # graft: guarded-by(_lock)
        self._hints: Dict[str, deque] = {}
        # in-flight handoff keys ("sid->replica"), for /fleet visibility
        # graft: guarded-by(_lock)
        self._handoffs = set()
        # model name -> {"version", "spec", "targets"} of the last
        # successful fleet-wide deploy: the rollback source
        # graft: guarded-by(_lock)
        self._specs: Dict[str, dict] = {}
        self._sid_counter = itertools.count(1)
        self.poll_interval = poll_interval
        self.auto_drain_on_slo = auto_drain_on_slo
        self.disaggregate = disaggregate
        self.handoff_min_tokens = int(handoff_min_tokens)
        self.unhealthy_after = int(unhealthy_after)
        self.registry = metrics if metrics is not None \
            else MetricsRegistry()
        m = self.registry
        self._c_requests = m.counter("fleet_requests_total")
        self._c_tokens = m.counter("fleet_tokens_streamed_total")
        self._c_reroutes = m.counter("fleet_reroutes_total")
        self._c_handoffs = m.counter("fleet_handoffs_total")
        self._c_handoff_fail = m.counter("fleet_handoff_failures_total")
        self._c_handoff_bytes = m.counter("fleet_handoff_bytes_total")
        self._c_migrations = m.counter("fleet_migrations_total")
        self._c_slo_drains = m.counter("fleet_slo_drains_total")
        self._c_deploys = m.counter("fleet_deploys_total")
        self._c_rollbacks = m.counter("fleet_deploy_rollbacks_total")
        self._c_failed = m.counter("fleet_failed_requests_total")
        self._g_replicas = m.gauge("fleet_replicas")
        self._g_healthy = m.gauge("fleet_replicas_healthy")
        self._g_draining = m.gauge("fleet_replicas_draining")
        self._g_inflight = m.gauge("fleet_inflight")
        self._h_ttft = m.histogram("fleet_ttft_ms")
        self._h_req = m.histogram("fleet_request_ms")
        for spec in replicas:
            if isinstance(spec, ReplicaHandle):
                self.add_replica(spec)
            elif isinstance(spec, dict):
                self.add_replica(ReplicaHandle(**spec))
            else:
                self.add_replica(ReplicaHandle(*spec))
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # the fleet observability plane: federation scrapes, trace
        # stitching, incident bundles — all pull-based, driven from
        # the poll loop (never from a stream's dispatch path)
        from deeplearning4j_tpu.serving.fleet.obsplane import (
            FleetObsPlane,
        )
        self.obsplane = FleetObsPlane(self)

    # ------------------------------------------------------------ topo
    def add_replica(self, handle: ReplicaHandle) -> None:
        with self._lock:
            self._replicas[handle.name] = handle
            self._hints.setdefault(
                handle.name, deque(maxlen=self.HINTS_PER_REPLICA))
            self._refresh_gauges_locked()

    def replica_urls(self) -> Dict[str, str]:
        """name -> url for every known replica (healthy or not): the
        obsplane's view of the fleet, copied under the lock so scrapes
        and stitches run with NO router lock held."""
        with self._lock:
            return {name: r.url for name, r in self._replicas.items()}

    def _refresh_gauges_locked(self) -> None:
        # graft: allow(GL301): every caller holds self._lock (the
        # *_locked naming contract, same as the pool's page API)
        reps = list(self._replicas.values())
        self._g_replicas.set(len(reps))
        self._g_healthy.set(sum(r.healthy for r in reps))
        self._g_draining.set(sum(r.draining for r in reps))
        self._g_inflight.set(sum(r.inflight for r in reps))

    # ------------------------------------------------------- lifecycle
    def start(self) -> int:
        port = super().start()
        if self.poll_interval:
            # graft: allow(GL301): lifecycle — start() runs before any
            # handler thread exists, nothing to race with yet
            self._stop.clear()
            # graft: allow(GL301): lifecycle — single-threaded start()
            self._poller = threading.Thread(
                target=self._poll_loop, name="fleet-poller", daemon=True)
            self._poller.start()
        return port

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=5.0)
            # graft: allow(GL301): lifecycle — poller already joined,
            # handlers are torn down by super().stop() next
            self._poller = None
        super().stop()

    # ------------------------------------------------------- placement
    @staticmethod
    def _lcp(a, b) -> int:
        n = min(len(a), len(b))
        for i in range(n):
            if a[i] != b[i]:
                return i
        return n

    def _overlap_locked(self, name: str, stem) -> int:
        # graft: allow(GL301): caller holds self._lock by contract
        hints = self._hints.get(name)
        if not hints:
            return 0
        return max((self._lcp(stem, h) for h in hints), default=0)

    def _place(self, stem, fleet_sid: Optional[str],
               exclude=(), *, roles=("decode", "mixed")) -> ReplicaHandle:
        """Pick a home: sticky session first, then prefix-overlap minus
        a load penalty, least-loaded tiebreak. Raises
        NoReplicaAvailableError when the candidate set is empty."""
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.healthy and not r.draining
                     and r.role in roles and r.name not in exclude]
            if not cands:
                raise NoReplicaAvailableError(
                    f"no healthy replica for roles {roles} "
                    f"(excluded: {sorted(exclude)})")
            if fleet_sid is not None:
                home = self._sessions.get(fleet_sid)
                for r in cands:
                    if r.name == home:
                        return r
            # overlap in tokens is worth more than a queued stream:
            # one cached page saves a whole prefill chunk of work
            best, best_score = None, None
            for r in cands:
                score = self._overlap_locked(r.name, stem) \
                    - 4 * r.inflight
                if best_score is None or score > best_score:
                    best, best_score = r, score
            return best

    def _note_stream_start_locked(self, r: ReplicaHandle,
                                  fleet_sid: str) -> None:
        # graft: allow(GL301): caller holds self._lock by contract
        r.inflight += 1
        # graft: allow(GL301): caller holds self._lock by contract
        self._sessions[fleet_sid] = r.name
        self._refresh_gauges_locked()

    def _note_stream_end(self, name: str, fleet_sid: str,
                         stem, history) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is not None and r.inflight > 0:
                r.inflight -= 1
            if stem:
                hints = self._hints.get(name)
                if hints is not None:
                    hints.append(tuple(stem))
            if fleet_sid not in self._history:
                self._history_order.append(fleet_sid)
                while len(self._history_order) > self.SESSION_HISTORY:
                    old = self._history_order.popleft()
                    self._history.pop(old, None)
                    self._sessions.pop(old, None)
            self._history[fleet_sid] = list(history)
            self._refresh_gauges_locked()

    def _mark_failure(self, name: str) -> None:
        """A network-level failure talking to `name`: bump the streak,
        and past the threshold stop placing anything there (the poller
        marks it healthy again when /healthz answers)."""
        crashed = False
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            r.fail_streak += 1
            if r.fail_streak >= self.unhealthy_after:
                crashed = r.healthy      # the healthy->dead transition
                r.healthy = False
            self._refresh_gauges_locked()
        if crashed:
            # collect evidence while the survivors still remember the
            # dead replica's streams (outside the lock: the collector
            # does network I/O)
            self.obsplane.trigger_incident(
                f"replica_crash_{name}", sorted(self.replica_urls()),
                {"replica": name})

    # ------------------------------------------------- disaggregation
    def _maybe_disaggregate(self, model: str, prompt: List[int],
                            target: ReplicaHandle, fleet_sid: str,
                            rt) -> None:
        """Prefill the stem on a prefill-role replica and hand the
        pages to `target`. Best-effort: any failure leaves the decode
        replica to prefill for itself (correctness never depends on a
        handoff landing)."""
        stem = prompt[:-1]
        if not self.disaggregate or \
                len(stem) < self.handoff_min_tokens:
            return
        with self._lock:
            prefillers = [r for r in self._replicas.values()
                          if r.healthy and not r.draining
                          and r.role == "prefill"
                          and r.name != target.name]
            if not prefillers:
                return
            if self._overlap_locked(target.name, stem) >= len(stem):
                return          # target already warm for this stem
            pf = min(prefillers, key=lambda r: r.inflight)
            key = f"{fleet_sid}->{target.name}"
            self._handoffs.add(key)
        t0 = time.monotonic()
        ok = False
        try:
            pre = client.post_json(
                pf.url, "/fleet/prefill",
                {"model": model, "prompt_ids": prompt}, timeout=60.0)
            if rt is not None:
                reqtrace.record_span(
                    rt.trace_id, "prefill.hop", parent_id=rt.span_id,
                    replica=pf.name, model=model,
                    replica_trace=pre.get("trace_id"),
                    prefill_ms=pre.get("prefill_ms"),
                    dur_ms=(time.monotonic() - t0) * 1000.0)
            payload = pre.get("payload")
            if payload is None:
                return
            t1 = time.monotonic()
            imp = client.post_json(
                target.url, "/fleet/kv/import",
                {"model": model, "payload": payload}, timeout=60.0)
            nbytes = payload_bytes(payload)
            self._c_handoffs.inc()
            self._c_handoff_bytes.inc(nbytes)
            ok = True
            if rt is not None:
                reqtrace.record_span(
                    rt.trace_id, "handoff", parent_id=rt.span_id,
                    src=pf.name, dst=target.name,
                    cached_len=imp.get("cached_len"),
                    pages=len(payload.get("pages", ())),
                    bytes=nbytes,
                    dur_ms=(time.monotonic() - t1) * 1000.0)
        except (client.ReplicaUnreachable, client.ReplicaHTTPError) as e:
            logger.warning("fleet handoff %s failed: %s",
                           f"{pf.name}->{target.name}", e)
            if isinstance(e, client.ReplicaUnreachable):
                self._mark_failure(pf.name)
        finally:
            if not ok:
                self._c_handoff_fail.inc()
            with self._lock:
                self._handoffs.discard(key)

    # ------------------------------------------------------- generate
    def _generate(self, req: dict):
        model = req.get("model", DEFAULT_MODEL)
        prompt = [int(t) for t in req["prompt_ids"]]   # KeyError → 400
        if not prompt:
            raise HttpError(400, "prompt_ids must be non-empty")
        fleet_sid = str(req.get("fleet_session")
                        or f"f{next(self._sid_counter):08d}")
        max_tokens = int(req.get("max_tokens", 16))
        rt = reqtrace.new_trace("fleet.generate")
        self._c_requests.inc()
        stem = tuple(prompt[:-1])
        try:
            target = self._place(stem, fleet_sid)
        except NoReplicaAvailableError as e:
            self._c_failed.inc()
            reqtrace.finish_root(rt, route="/generate", status=503)
            raise HttpError(503, str(e))
        if rt is not None:
            reqtrace.record_span(rt.trace_id, "route",
                                 parent_id=rt.span_id,
                                 replica=target.name, model=model,
                                 fleet_session=fleet_sid)
        self._maybe_disaggregate(model, prompt, target, fleet_sid, rt)
        with self._lock:
            self._note_stream_start_locked(target, fleet_sid)
        body = {k: req[k] for k in
                ("temperature", "top_k", "top_p", "greedy", "seed",
                 "deadline_ms", "eos_id") if req.get(k) is not None}
        body.update({"model": model, "prompt_ids": prompt,
                     "max_tokens": max_tokens, "stream": True})
        if req.get("stream", True):
            return StreamResponse(self._proxy_stream(
                model, prompt, body, target, fleet_sid, rt))
        # non-stream: drain our own proxy generator so failover applies
        tokens, outcome, error = [], None, None
        for ev in self._proxy_stream(model, prompt, body, target,
                                     fleet_sid, rt):
            if "token" in ev:
                tokens.append(ev["token"])
            elif "error" in ev:
                error, outcome = ev["error"], ev.get("outcome")
            elif "done" in ev:
                outcome = ev.get("outcome")
        if error is not None:
            raise HttpError(500, f"fleet generate failed: {error}")
        return {"fleet_session": fleet_sid, "model": model,
                "tokens": tokens, "outcome": outcome,
                **({"trace_id": rt.trace_id} if rt is not None else {})}

    def _proxy_stream(self, model: str, prompt: List[int], body: dict,
                      target: ReplicaHandle, fleet_sid: str, rt):
        """Yield client-facing SSE events, failing over to another
        replica when the current one dies mid-stream. Token indices are
        re-numbered router-side so the resumed stream is seamless; the
        resume prompt is `prompt + tokens_so_far`, which for greedy
        sampling continues the identical sequence (the chaos suite
        pins byte-equality against an uninterrupted run)."""
        t0 = time.monotonic()
        emitted: List[int] = []
        max_tokens = int(body["max_tokens"])
        current = target
        failovers = 0
        ttft_seen = False
        first = {"fleet_session": fleet_sid, "replica": current.name,
                 "model": model}
        if rt is not None:
            first["trace_id"] = rt.trace_id
        yield first
        try:
            while True:
                attempt_body = dict(body)
                if emitted:
                    # resume after a failover: everything streamed so
                    # far becomes prompt, budget shrinks accordingly
                    attempt_body["prompt_ids"] = prompt + emitted
                    attempt_body["max_tokens"] = \
                        max_tokens - len(emitted)
                    attempt_body["_migration"] = True
                hop_t0 = time.monotonic()
                hop_sess = None
                try:
                    for ev in client.sse_events(current.url, "/generate",
                                                attempt_body,
                                                timeout=120.0):
                        if "token" in ev:
                            if not ttft_seen:
                                ttft_seen = True
                                self._h_ttft.observe(
                                    (time.monotonic() - t0) * 1000.0)
                            emitted.append(int(ev["token"]))
                            self._c_tokens.inc()
                            yield {"token": ev["token"],
                                   "index": len(emitted) - 1,
                                   "replica": current.name}
                        elif "session" in ev:
                            hop_sess = ev.get("session")
                            if rt is not None:
                                reqtrace.record_span(
                                    rt.trace_id, "decode.hop",
                                    parent_id=rt.span_id,
                                    replica=current.name,
                                    session=hop_sess,
                                    replica_trace=ev.get("trace_id"),
                                    resumed=bool(failovers))
                        elif "done" in ev or "error" in ev:
                            # a replica-REPORTED terminal (deadline,
                            # cancel, …): the replica is alive, this
                            # is the stream's real verdict — forward
                            out = dict(ev)
                            out["fleet_session"] = fleet_sid
                            out["tokens"] = len(emitted)
                            if "error" in ev:
                                self._c_failed.inc()
                            yield out
                            return
                except client.ReplicaUnreachable as e:
                    self._mark_failure(current.name)
                    self._c_reroutes.inc()
                    failovers += 1
                    if rt is not None:
                        reqtrace.record_span(
                            rt.trace_id, "failover",
                            parent_id=rt.span_id, dead=current.name,
                            session=hop_sess, error=str(e)[:200],
                            tokens_so_far=len(emitted),
                            dur_ms=(time.monotonic() - hop_t0)
                            * 1000.0)
                    # detached collector: never slows this stream's
                    # own failover
                    self.obsplane.trigger_incident(
                        f"failover_{current.name}",
                        sorted(self.replica_urls()),
                        {"dead": current.name,
                         "fleet_session": fleet_sid,
                         "tokens_so_far": len(emitted),
                         **({"trace_id": rt.trace_id}
                            if rt is not None else {})})
                    if failovers > self.MAX_FAILOVERS:
                        self._c_failed.inc()
                        yield {"error": f"stream failed after "
                               f"{failovers} replicas: {e}",
                               "fleet_session": fleet_sid,
                               "tokens": len(emitted)}
                        return
                    if len(emitted) >= max_tokens:
                        # the budget was already met when the replica
                        # died on the terminal frame — finish cleanly
                        yield {"done": True, "outcome": "completed",
                               "fleet_session": fleet_sid,
                               "tokens": len(emitted)}
                        return
                    try:
                        nxt = self._place(
                            tuple(prompt[:-1]), None,
                            exclude={current.name})
                    except NoReplicaAvailableError as e2:
                        self._c_failed.inc()
                        yield {"error": str(e2),
                               "fleet_session": fleet_sid,
                               "tokens": len(emitted)}
                        return
                    with self._lock:
                        self._note_stream_start_locked(nxt, fleet_sid)
                        cur = self._replicas.get(current.name)
                        if cur is not None and cur.inflight > 0:
                            cur.inflight -= 1
                    current = nxt
                except client.ReplicaHTTPError as e:
                    # alive but refusing (503 draining / slots full):
                    # place elsewhere without marking it dead
                    self._c_reroutes.inc()
                    failovers += 1
                    if failovers > self.MAX_FAILOVERS:
                        self._c_failed.inc()
                        yield {"error": str(e),
                               "fleet_session": fleet_sid,
                               "tokens": len(emitted)}
                        return
                    try:
                        nxt = self._place(tuple(prompt[:-1]), None,
                                          exclude={current.name})
                    except NoReplicaAvailableError as e2:
                        self._c_failed.inc()
                        yield {"error": str(e2),
                               "fleet_session": fleet_sid,
                               "tokens": len(emitted)}
                        return
                    with self._lock:
                        self._note_stream_start_locked(nxt, fleet_sid)
                        cur = self._replicas.get(current.name)
                        if cur is not None and cur.inflight > 0:
                            cur.inflight -= 1
                    current = nxt
        finally:
            self._h_req.observe((time.monotonic() - t0) * 1000.0)
            self._note_stream_end(current.name, fleet_sid,
                                  prompt[:-1], prompt + emitted)
            if rt is not None:
                reqtrace.finish_root(
                    rt, route="/generate", model=model,
                    fleet_session=fleet_sid, tokens=len(emitted),
                    failovers=failovers, replica=current.name)

    # -------------------------------------------------- drain/migrate
    def drain_replica(self, name: str, *, migrate: bool = True,
                      reason: str = "manual") -> dict:
        """Mark `name` draining, stop placing new sessions there, and
        migrate its sticky sessions' warm KV stems into healthy
        replicas through export → install. Live streams keep running
        on the draining replica until they finish (drain ≠ kill)."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                raise HttpError(404, f"unknown replica {name!r}")
            r.draining = True
            self._refresh_gauges_locked()
            moved_sids = [sid for sid, home in self._sessions.items()
                          if home == name]
            history = {sid: list(self._history.get(sid, ()))
                       for sid in moved_sids}
        rt = reqtrace.new_trace("fleet.drain")
        try:
            client.post_json(r.url, "/fleet/drain", {"draining": True},
                             timeout=10.0)
        # graft: allow(GL403): best-effort notify — the drain proceeds
        # router-side regardless; an unreachable replica is already
        # effectively drained of new traffic
        except (client.ReplicaUnreachable, client.ReplicaHTTPError):
            pass
        migrated, failed = 0, 0
        for sid in moved_sids:
            toks = history.get(sid) or []
            try:
                dst = self._place(tuple(toks), None, exclude={name})
            except NoReplicaAvailableError:
                failed += len(moved_sids) - migrated - failed
                break
            ok = False
            if migrate and toks:
                try:
                    exp = client.post_json(
                        r.url, "/fleet/kv/export",
                        {"tokens": toks}, timeout=60.0)
                    payload = exp.get("payload")
                    if payload is not None:
                        client.post_json(
                            dst.url, "/fleet/kv/import",
                            {"payload": payload}, timeout=60.0)
                        ok = True
                        self._c_handoff_bytes.inc(
                            payload_bytes(payload))
                except (client.ReplicaUnreachable,
                        client.ReplicaHTTPError) as e:
                    logger.warning("drain migration of %s failed: %s",
                                   sid, e)
            with self._lock:
                self._sessions[sid] = dst.name
                hints = self._hints.get(dst.name)
                if hints is not None and toks:
                    hints.append(tuple(toks))
            migrated += 1
            self._c_migrations.inc()
            if rt is not None:
                reqtrace.record_span(
                    rt.trace_id, "migrate", parent_id=rt.span_id,
                    session=sid, src=name, dst=dst.name,
                    kv_handed_off=ok, tokens=len(toks))
        if rt is not None:
            reqtrace.finish_root(rt, replica=name, reason=reason,
                                 migrated=migrated, failed=failed)
        return {"replica": name, "draining": True, "reason": reason,
                "migrated": migrated, "failed": failed}

    def undrain_replica(self, name: str) -> dict:
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                raise HttpError(404, f"unknown replica {name!r}")
            r.draining = False
            r.slo_drained = False
            self._refresh_gauges_locked()
        try:
            client.post_json(r.url, "/fleet/drain", {"draining": False},
                             timeout=10.0)
        # graft: allow(GL403): best-effort notify — router-side routing
        # state is authoritative; the poller reconciles replica state
        except (client.ReplicaUnreachable, client.ReplicaHTTPError):
            pass
        return {"replica": name, "draining": False}

    # ------------------------------------------------------- SLO loop
    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            # the control loop must survive any single poll's failure —
            # the next tick retries, and the log keeps the evidence
            # graft: allow(GL403): control loop logs and retries
            except Exception:
                logger.exception("fleet poll failed")

    def poll_once(self) -> dict:
        """One control tick: refresh every replica's health from its
        /healthz, and drain any replica whose burn-rate SLO is firing
        (traffic reroutes; its sessions migrate out warm)."""
        with self._lock:
            snapshot = list(self._replicas.values())
        verdicts = {}
        to_drain = []
        for r in snapshot:
            try:
                hz = client.get_json(r.url, "/healthz", timeout=5.0)
            except (client.ReplicaUnreachable,
                    client.ReplicaHTTPError) as e:
                verdicts[r.name] = f"unreachable: {e}"
                self._mark_failure(r.name)
                continue
            slo_firing = [s for s in hz.get("reasons", ())
                          if s.startswith("slo firing")]
            verdicts[r.name] = (hz.get("status", "?")
                                + (f" ({'; '.join(slo_firing)})"
                                   if slo_firing else ""))
            with self._lock:
                r.fail_streak = 0
                r.healthy = True
                if r.slo_drained and not slo_firing:
                    # breach cleared: lift the automatic drain
                    r.draining = False
                    r.slo_drained = False
                want_drain = (self.auto_drain_on_slo and slo_firing
                              and not r.draining)
                if want_drain:
                    r.slo_drained = True
                self._refresh_gauges_locked()
            if want_drain:
                to_drain.append((r.name, "; ".join(slo_firing)))
        for name, reason in to_drain:
            self._c_slo_drains.inc()
            logger.warning("fleet: draining %s (%s)", name, reason)
            self.obsplane.trigger_incident(
                f"slo_drain_{name}", sorted(self.replica_urls()),
                {"replica": name, "reason": reason})
            try:
                self.drain_replica(name, reason=f"slo: {reason}")
            # graft: allow(GL403): replica vanished between verdict and
            # drain — the next poll round marks it unhealthy anyway
            except HttpError:
                pass
        # federation tick rides the same poll: scrape every replica's
        # registry, merge, and evaluate the fleet-scope SLOs
        try:
            self.obsplane.scrape_once()
        # graft: allow(GL403): federation is advisory — a failed scrape
        # must not take down the health poll; the next tick retries
        except Exception:
            logger.exception("fleet scrape failed")
        return verdicts

    # -------------------------------------------- coordinated deploy
    def _fleet_deploy(self, req: dict):
        """Deploy `targets` (e.g. `<model>` and `<model>@draft`) across
        EVERY replica as one transaction: any replica's deploy-watchdog
        trip rolls back every already-flipped (replica, target) pair to
        the previous fleet spec."""
        targets = req.get("targets")
        if targets is None:
            if not isinstance(req.get("spec"), dict):
                raise HttpError(400,
                                "deploy needs targets=[...] or "
                                "{name, version, spec}")
            targets = [{"name": req.get("name", DEFAULT_MODEL),
                        "version": req.get("version"),
                        "spec": req["spec"]}]
        for t in targets:
            if t.get("version") is None or \
                    not isinstance(t.get("spec"), dict):
                raise HttpError(400, f"bad deploy target: {t}")
        with self._lock:
            replicas = [r for r in self._replicas.values() if r.healthy]
            prev = {t["name"]: self._specs.get(t["name"])
                    for t in targets}
        rt = reqtrace.new_trace("fleet.deploy")
        done = []               # (replica, target) pairs flipped
        failure = None
        for r in replicas:
            for t in targets:
                t0 = time.monotonic()
                try:
                    res = client.post_json(
                        r.url, "/fleet/deploy",
                        {"name": t["name"], "version": t["version"],
                         "spec": t["spec"]}, timeout=120.0)
                except (client.ReplicaUnreachable,
                        client.ReplicaHTTPError) as e:
                    res = {"ok": False, "error": str(e)}
                if rt is not None:
                    reqtrace.record_span(
                        rt.trace_id, "deploy.hop",
                        parent_id=rt.span_id, replica=r.name,
                        target=t["name"], ok=res.get("ok", False),
                        rolled_back=res.get("rolled_back", False),
                        dur_ms=(time.monotonic() - t0) * 1000.0)
                if not res.get("ok"):
                    failure = {"replica": r.name, "target": t["name"],
                               "error": res.get("error", "deploy "
                                                "failed")}
                    break
                done.append((r, t))
            if failure:
                break
        if failure is None:
            with self._lock:
                for t in targets:
                    self._specs[t["name"]] = {
                        "version": t["version"], "spec": t["spec"]}
                # new weights mean every replica's radix flushed: the
                # router's overlap hints are stale, drop them
                for hints in self._hints.values():
                    hints.clear()
            self._c_deploys.inc()
            if rt is not None:
                reqtrace.finish_root(rt, ok=True,
                                     replicas=len(replicas),
                                     targets=len(targets))
            return {"ok": True, "replicas": [r.name for r in replicas],
                    "targets": [t["name"] for t in targets],
                    **({"trace_id": rt.trace_id}
                       if rt is not None else {})}
        # rollback everywhere that already flipped
        rolled, rollback_errors = [], []
        for r, t in done:
            pv = prev.get(t["name"])
            if pv is None:
                rollback_errors.append(
                    {"replica": r.name, "target": t["name"],
                     "error": "no previous fleet spec recorded"})
                continue
            try:
                res = client.post_json(
                    r.url, "/fleet/deploy",
                    {"name": t["name"], "version": pv["version"],
                     "spec": pv["spec"]}, timeout=120.0)
                if res.get("ok"):
                    rolled.append({"replica": r.name,
                                   "target": t["name"]})
                else:
                    rollback_errors.append(
                        {"replica": r.name, "target": t["name"],
                         "error": res.get("error", "rollback failed")})
            except (client.ReplicaUnreachable,
                    client.ReplicaHTTPError) as e:
                rollback_errors.append(
                    {"replica": r.name, "target": t["name"],
                     "error": str(e)})
        self._c_rollbacks.inc()
        self.obsplane.trigger_incident(
            "deploy_rollback", sorted(self.replica_urls()),
            {"failure": failure, "rolled_back": len(rolled),
             "rollback_errors": len(rollback_errors)})
        if rt is not None:
            reqtrace.finish_root(rt, ok=False,
                                 failed_replica=failure["replica"],
                                 rolled_back=len(rolled))
        return {"ok": False, "failure": failure, "rolled_back": rolled,
                "rollback_errors": rollback_errors,
                **({"trace_id": rt.trace_id}
                   if rt is not None else {})}

    # ---------------------------------------------------------- routes
    def _fleet(self, request=None):
        q = (request or {}).get("query", {})
        refresh = bool(q.get("refresh"))
        with self._lock:
            out = {"replicas": [r.describe()
                                for r in self._replicas.values()],
                   "sessions": len(self._sessions),
                   "handoffs_inflight": sorted(self._handoffs),
                   "specs": {k: v["version"]
                             for k, v in self._specs.items()}}
        if refresh:
            infos = {}
            for rep in out["replicas"]:
                try:
                    infos[rep["name"]] = client.get_json(
                        rep["url"], "/fleet/info", timeout=5.0)
                except (client.ReplicaUnreachable,
                        client.ReplicaHTTPError) as e:
                    infos[rep["name"]] = {"error": str(e)}
            out["info"] = infos
        return out

    def _healthz(self):
        with self._lock:
            reps = list(self._replicas.values())
            healthy = sum(1 for r in reps
                          if r.healthy and not r.draining)
        reasons = []
        if not healthy:
            reasons.append("no healthy replica")
        return {"status": "degraded" if reasons else "ok",
                "reasons": reasons, "tier": "router",
                "replicas": len(reps), "routable": healthy}

    def _metrics(self, request=None):
        from deeplearning4j_tpu.serving.inference_server import (
            InferenceServer,
        )
        if request is not None and \
                InferenceServer._wants_prometheus(request):
            return TextResponse(self.registry.to_prometheus(),
                                content_type=PROMETHEUS_CONTENT_TYPE)
        snap = self.registry.snapshot()
        with self._lock:
            snap["fleet"] = {
                "replicas": [r.describe()
                             for r in self._replicas.values()],
                "sessions": len(self._sessions)}
        return snap

    def _drain_route(self, req: dict):
        name = req.get("replica")
        if not name:
            raise HttpError(400, "need {replica: name}")
        if req.get("draining", True):
            return self.drain_replica(
                name, migrate=bool(req.get("migrate", True)))
        return self.undrain_replica(name)

    def _fleet_metrics(self, request=None):
        """GET /fleet/metrics — the federated view: every replica's
        scraped registry merged (restart-safe counter deltas,
        bucket-wise histograms, replica-labeled gauges), scrape
        staleness per replica, and the fleet SLO verdicts."""
        q = (request or {}).get("query", {})
        if q.get("refresh"):
            self.obsplane.scrape_once()
        return self.obsplane.metrics_payload()

    def _fleet_series(self, request=None):
        """GET /fleet/series — the fleet SeriesStore the SLO engine
        burns over (same query params as a replica's /series)."""
        q = (request or {}).get("query", {})

        def _f(name):
            try:
                return float(q[name][0]) if q.get(name) else None
            except (TypeError, ValueError):
                raise HttpError(400, f"bad {name!r} query param")
        out = self.obsplane.store.snapshot(
            window_s=_f("window"),
            prefix=(q.get("prefix") or [None])[0])
        out["scrapes"] = self.obsplane.scrapes
        return out

    def _trace_list(self):
        store = reqtrace.get_trace_store()
        ids = store.ids()
        return {"traces": ids[-50:], "count": len(ids),
                "sample_rate": reqtrace.sample_rate()}

    def _trace(self, suffix: str, request=None):
        """GET /trace/{id} — the router's tree with every hop's replica
        subtree grafted in (one cross-process waterfall). `?raw=1`
        returns the unstitched router-local tree."""
        tid = suffix.strip("/")
        if not tid:
            return self._trace_list()
        q = (request or {}).get("query", {})
        doc = self.obsplane.stitched_trace(tid, raw=bool(q.get("raw")))
        if doc is None:
            raise HttpError(404, f"unknown trace: {tid!r}")
        return doc

    def get_routes(self):
        return {"/fleet": self._fleet, "/healthz": self._healthz,
                "/metrics": self._metrics,
                "/fleet/metrics": self._fleet_metrics,
                "/fleet/series": self._fleet_series,
                "/trace": self._trace_list}

    def get_prefix_routes(self):
        return {"/trace/": self._trace}

    def post_routes(self):
        return {"/generate": self._generate,
                "/fleet/drain": self._drain_route,
                "/fleet/deploy": self._fleet_deploy}
