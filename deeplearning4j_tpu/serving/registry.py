"""ModelRegistry — multiple named+versioned models behind one server,
with atomic zero-downtime hot-swap.

The registry owns the data plane for each deployed model: a
`ParallelInference` runner (bucketed pad + per-bucket jit cache + the
oversize chunking fix in `parallel/inference.py`). The serving
scheduler dispatches through `acquire()/release()`, which is also the
hot-swap seam:

  deploy(name, version, net)
    1. builds the new entry's runner and WARMS its bucketed jit caches
       (`ParallelInference.warmup`) while the old version keeps serving —
       no live request ever pays the new version's compiles;
    2. flips the active pointer under the registry lock — atomic with
       `acquire`, so a request routes to exactly one version;
    3. drains the old entry (waits for its in-flight batches to
       complete) and shuts its runner down.

Requests acquired on the old version finish on the old version;
requests admitted after the flip run on the new one. Nothing is
dropped, which is the zero-downtime contract the hot-swap test pins.

Reference precedent: the reference serves models via ParallelInference
embedded in user code; the registry is the missing control plane the
DL4J model-server modules (NearestNeighborsServer, Keras gateway)
imply.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.parallel.inference import (
    InferenceMode, ParallelInference,
)

logger = logging.getLogger("deeplearning4j_tpu")


class DeployRolledBackError(RuntimeError):
    """`deploy()` refused to flip: warmup crashed or tripped the
    recompile watchdog, and the previously active version (when one
    exists) was left serving. The failed runner is already shut down."""


class ModelEntry:
    """One deployed (name, version): net + warmed runner + in-flight
    accounting for drain-on-swap."""

    def __init__(self, name: str, version, net,
                 runner: ParallelInference):
        self.name = name
        self.version = version
        self.net = net
        self.runner = runner
        self.deployed_at = time.time()
        self.served = 0
        self._inflight = 0
        self._cv = threading.Condition()
        self._retired = False

    # ------------------------------------------------------ data plane
    def run_batch(self, xs):
        return self.runner.run_batch(xs)

    def output(self, x):
        """Collect-mode path: goes through the runner's own collector
        queue when the runner is BATCHED, direct otherwise."""
        return self.runner.output(x)

    # ------------------------------------------------------- lifecycle
    def _drain(self, timeout: Optional[float]) -> bool:
        with self._cv:
            return self._cv.wait_for(lambda: self._inflight == 0, timeout)

    def describe(self) -> dict:
        with self._cv:
            return {"version": self.version,
                    "deployed_at": round(self.deployed_at, 3),
                    "served": self.served,
                    "inflight": self._inflight,
                    "retired": self._retired}


class ModelRegistry:
    """Named models, one active version each, atomic hot-swap."""

    def __init__(self, *, mesh=None, max_batch_size: int = 64,
                 batch_buckets: Optional[List[int]] = None,
                 runner_mode: str = InferenceMode.INPLACE,
                 collect_wait_ms: float = 5.0,
                 drain_timeout_s: float = 30.0):
        self.mesh = mesh
        self.max_batch = max_batch_size
        self.buckets = batch_buckets
        self.runner_mode = runner_mode
        self.collect_wait_ms = collect_wait_ms
        self.drain_timeout = drain_timeout_s
        self._lock = threading.Lock()
        self._active: Dict[str, ModelEntry] = {}
        self._history: Dict[str, List] = {}
        self._deploy_hooks: Dict[str, List] = {}

    # ---------------------------------------------------------- deploy
    @staticmethod
    def _infer_feat_shape(net):
        """Best-effort single-input feature shape for warmup, from the
        config's InputType (the repo's single source of shape truth)."""
        try:
            it = net.conf.input_type
            shape = it.shape(1)[1:]
            return shape if all(d for d in shape) else None
        except Exception:
            return None

    def deploy(self, name: str, version, net, *, feat_shape=None,
               warm: bool = True) -> ModelEntry:
        """Deploy `net` as the active version of `name`; returns the new
        entry after the old one (if any) is drained and retired.

        Failover (ISSUE 6): warmup is the canary. If it raises, or it
        trips the RecompileWatchdog on the new runner's jit cache (the
        version would recompile under live traffic — the silent-10x
        outage), the flip never happens: the previous version keeps
        serving untouched and `DeployRolledBackError` is raised. A
        watchdog trip on a FIRST deploy (nothing to roll back to)
        proceeds with a warning — degraded beats dark."""
        with self._lock:
            cur = self._active.get(name)
        if cur is not None and getattr(cur, "_external", False):
            raise ValueError(
                f"{name!r} is an externally-managed entry "
                f"(register_entry); deploy() cannot replace it")
        runner = ParallelInference(
            net, mesh=self.mesh, mode=self.runner_mode,
            max_batch_size=self.max_batch, batch_buckets=self.buckets,
            max_wait_ms=self.collect_wait_ms)
        entry = ModelEntry(name, version, net, runner)
        if warm:
            shape = feat_shape or self._infer_feat_shape(net)
            if shape:
                failure: Optional[BaseException] = None
                try:
                    runner.warmup(shape)
                except BaseException as e:
                    failure = e
                tripped = failure is None and self._warmup_tripped(runner)
                with self._lock:
                    has_previous = name in self._active
                if failure is not None or (tripped and has_previous):
                    self._reject_deploy(name, version, runner,
                                        cause=failure, tripped=tripped,
                                        has_previous=has_previous)
                elif tripped:
                    logger.warning(
                        "deploy(%s@%r): warmup tripped the recompile "
                        "watchdog but no previous version exists — "
                        "deploying anyway (degraded beats dark)",
                        name, version)
            # warm-phase deploy hooks join the canary: a decode-session
            # manager pre-compiles the candidate's session-step buckets
            # here (so live sessions never pay a post-flip compile) and
            # RAISES if live sessions could not migrate onto it — which
            # rides the same rollback path, previous version untouched.
            for hook in self._hooks_for(name):
                try:
                    hook("warm", name, version, net)
                except BaseException as e:
                    with self._lock:
                        has_previous = name in self._active
                    self._reject_deploy(name, version, runner,
                                        cause=e, tripped=False,
                                        has_previous=has_previous)
        with self._lock:
            old = self._active.get(name)
            self._active[name] = entry
            self._history.setdefault(name, []).append(
                {"version": version, "at": round(time.time(), 3)})
        # flipped-phase hooks run after the pointer swap but before the
        # old entry drains, so live decode sessions rebind to the new
        # net while the old version is still able to finish its last
        # in-flight batches. A hook failure here must not wedge the
        # deploy — the flip already happened; log and keep going.
        for hook in self._hooks_for(name):
            try:
                hook("flipped", name, version, net)
            # graft: allow(GL403): post-flip migration is best-effort —
            # the deploy is already live; failure is logged + recorded
            except Exception as e:
                logger.warning(
                    "deploy(%s@%r): post-flip hook failed: %s",
                    name, version, e)
                try:
                    from deeplearning4j_tpu.observe import get_flight
                    get_flight().record(
                        "deploy_hook_failed", model=name,
                        version=version, error=type(e).__name__)
                # graft: allow(GL403): telemetry stays best-effort
                except Exception:
                    pass
        if old is not None:
            self._retire(old)
        return entry

    # ---------------------------------------------- entries and hooks
    def register_entry(self, name: str, entry: ModelEntry) -> ModelEntry:
        """Register an externally-managed entry (e.g. a decode-session
        endpoint whose `runner` is a session manager, not a
        ParallelInference). It participates in acquire/release/summary/
        close exactly like a deployed model, but `deploy()` under the
        same name is refused — its lifecycle belongs to its owner."""
        with self._lock:
            if name in self._active:
                raise ValueError(f"entry {name!r} already registered")
            entry._external = True
            self._active[name] = entry
            self._history.setdefault(name, []).append(
                {"version": entry.version, "at": round(time.time(), 3)})
        return entry

    def add_deploy_hook(self, name: str, hook) -> None:
        """Subscribe `hook(phase, name, version, net)` to deploys of
        `name`. phase is "warm" (inside the canary, pre-flip; raising
        rolls the deploy back) or "flipped" (after the atomic pointer
        swap; failures are logged, never propagated)."""
        with self._lock:
            self._deploy_hooks.setdefault(name, []).append(hook)

    def remove_deploy_hook(self, name: str, hook) -> None:
        with self._lock:
            hooks = self._deploy_hooks.get(name, [])
            if hook in hooks:
                hooks.remove(hook)

    def _hooks_for(self, name: str) -> List:
        with self._lock:
            return list(self._deploy_hooks.get(name, []))

    @staticmethod
    def _warmup_tripped(runner: ParallelInference) -> bool:
        """Did warming THIS runner's jit cache cross the watchdog's churn
        threshold? The tag is per-instance, so a trip here is the new
        version's own compile churn, never residue from an old one."""
        from deeplearning4j_tpu.observe.watchdog import get_watchdog
        return get_watchdog().warned(runner._jit_cache.owner_tag)

    def _reject_deploy(self, name, version, runner, *, cause, tripped,
                       has_previous):
        """Tear down the failed candidate and raise; the active pointer
        was never touched, so the old version (if any) keeps serving."""
        try:
            runner.shutdown()
        # graft: allow(GL403): best-effort teardown of a runner that
        # already failed — the rollback error below is the payload
        except Exception:
            pass
        reason = ("warmup raised" if cause is not None
                  else "warmup tripped the recompile watchdog")
        try:
            from deeplearning4j_tpu.observe import get_flight, get_registry
            get_registry().counter("serving_deploy_rollbacks_total",
                                   model=name).inc()
            get_flight().record(
                "deploy_rollback", model=name, version=version,
                reason=reason, watchdog_tripped=bool(tripped),
                previous_kept=bool(has_previous),
                error=None if cause is None else type(cause).__name__)
        # graft: allow(GL403): telemetry must not mask the rollback error
        except Exception:
            pass
        logger.warning(
            "deploy(%s@%r) rolled back: %s%s", name, version, reason,
            " — previous version keeps serving" if has_previous
            else " — model has no active version")
        raise DeployRolledBackError(
            f"deploy {name}@{version!r} rolled back: {reason}"
        ) from cause

    def undeploy(self, name: str):
        with self._lock:
            old = self._active.pop(name)
        self._retire(old)

    def _retire(self, entry: ModelEntry):
        entry._drain(self.drain_timeout)
        with entry._cv:
            entry._retired = True
        entry.runner.shutdown()

    # ------------------------------------------------- scheduler SPI
    def acquire(self, name: str) -> ModelEntry:
        """Pin the active entry for one dispatch. Atomic with deploy's
        flip (same lock), so the old version's drain can never miss a
        racing dispatch. KeyError for unknown models (HTTP 400)."""
        with self._lock:
            entry = self._active[name]
            with entry._cv:
                entry._inflight += 1
                entry.served += 1
        return entry

    def release(self, entry: ModelEntry):
        with entry._cv:
            entry._inflight -= 1
            entry._cv.notify_all()

    # ------------------------------------------------------- inspection
    def get(self, name: str) -> ModelEntry:
        with self._lock:
            return self._active[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._active)

    def summary(self) -> dict:
        """/models payload."""
        with self._lock:
            entries = dict(self._active)
            history = {n: list(h) for n, h in self._history.items()}
        return {name: dict(entry.describe(),
                           deployments=len(history.get(name, ())))
                for name, entry in sorted(entries.items())}

    def close(self):
        with self._lock:
            entries = list(self._active.values())
            self._active.clear()
        for e in entries:
            self._retire(e)
