"""PrefixCache — a page-granular radix index over shared KV pages.

The paged `KVSlotPool` stores attention KV in fixed-size pages; this
module decides which pages are worth sharing. It is a trie keyed on
token content at page granularity: every full node is one immutable KV
page holding exactly `page_len` tokens, edges are the page's token
tuple (dict lookup — matching a full page is one O(1) probe, not a
token walk), and each node additionally carries *partial* leaves for
prefixes that end mid-page. The PyGraph lesson from the serving
roadmap, applied to prefill: a prompt whose prefix is already resident
re-executes nothing — admission points the new session's page table at
the matched chain and prefill starts at the divergence point.

Sharing contract (mechanism in kv_pool, policy here):

- Matched FULL pages are adopted by reference (`page_ref_locked`) and
  are read-only from every follower's point of view: a follower's
  writes all land at positions >= its cached prefix, which live in
  later pages. The donor may still be decoding, but its writes land at
  positions >= its own prefill stem — beyond every full prefix page —
  so full pages are immutable by construction, no freeze-copy needed.
- A match that ends mid-page triggers the ONE copy-on-write fork of an
  admission: the partial page is copied to a fresh private page and
  the follower writes from the divergence offset. At most one page is
  ever copied per session open.
- Insert adopts the *donor's* pages (one extra refcount held by the
  cache). A donor's tail page is adopted as a partial leaf even though
  the donor keeps appending into it: followers fork it before writing
  and only read offsets below the recorded token count, which prefill
  finalized — and every copy/install runs under the pool lock, so it
  serializes with decode windows.
- Eviction is leaf-first LRU and may only reclaim pages whose pool
  refcount is exactly 1 (the cache's own reference): a page any live
  session maps stays resident no matter how cold its chain goes.

Quantized (int8/fp8) pages share bit-exactly: dequantization scales
are stored per-(token, kv-head) inside the page itself, so a follower
reading a shared page applies the very scales the donor's prefill
wrote — there is no per-session calibration to diverge. The tier-1
suite asserts cross-session bit-equality on shared quantized pages.

Thread-safety: every method must be called with the pool lock held
(the same `with pool.lock():` critical section that covers page
alloc/install), mirroring the `*_locked` pool API. The cache keeps no
lock of its own.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


class _Node:
    """One cached full page (the root holds no page). `children` maps
    a full page's token tuple to the next node; `partials` are
    (token_tuple, physical_page, tick) leaves for chains ending
    mid-page."""

    __slots__ = ("page", "children", "partials", "tick")

    def __init__(self, page: Optional[int] = None):
        self.page = page
        self.children = {}
        self.partials = []
        self.tick = 0


def _lcp(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class PrefixCache:
    """Radix index mapping token prefixes to refcounted page chains."""

    def __init__(self, pool, *, metrics=None):
        self.pool = pool
        self.page_len = pool.page_len
        if not self.page_len:
            raise ValueError("PrefixCache requires a paged KVSlotPool")
        self._root = _Node()
        self._tick = 0
        if metrics is None:
            from deeplearning4j_tpu.observe import get_registry
            metrics = get_registry()
        m = pool.model
        self._c_hits = metrics.counter("prefix_cache_hits_total", model=m)
        self._c_misses = metrics.counter("prefix_cache_misses_total",
                                         model=m)
        self._c_hit_tokens = metrics.counter("prefix_cache_hit_tokens_total",
                                             model=m)
        self._c_evicted = metrics.counter("prefix_cache_evicted_pages_total",
                                          model=m)
        self._c_inserts = metrics.counter("prefix_cache_inserts_total",
                                          model=m)
        self._c_cow = metrics.counter("prefix_cache_cow_forks_total",
                                      model=m)

    # ------------------------------------------------------------ match
    def match(self, tokens) -> Tuple[int, List[int], Optional[Tuple[int,
                                                                    int]]]:
        """Longest cached prefix of `tokens`. Returns `(cached_len,
        full_pages, partial)` where `full_pages` are physical ids of
        whole matched pages (adopt by reference) and `partial` is
        `(physical_page, n_tokens)` when the match ends mid-page (fork
        before use) or None. Counts a hit iff cached_len > 0. Caller
        holds the pool lock."""
        toks = tuple(int(t) for t in tokens)
        Lp = self.page_len
        self._tick += 1
        node, pages, off = self._root, [], 0
        while off + Lp <= len(toks):
            child = node.children.get(toks[off:off + Lp])
            if child is None:
                break
            child.tick = self._tick
            pages.append(child.page)
            node, off = child, off + Lp
        # tail: longest common prefix against one more page's worth of
        # content — a full child's edge or a partial leaf
        tail = toks[off:off + Lp]
        best_len, best_page = 0, None
        best_child, best_pidx = None, None
        if tail:
            for edge, child in node.children.items():
                k = _lcp(tail, edge)
                if k > best_len:
                    best_len, best_page = k, child.page
                    best_child, best_pidx = child, None
            for i, (ptoks, ppage, _) in enumerate(node.partials):
                k = _lcp(tail, ptoks)
                if k > best_len:
                    best_len, best_page = k, ppage
                    best_child, best_pidx = None, i
        # refresh the winner's LRU tick: a partially-matched page is as
        # hot as a fully-matched one — without this, recently-hit
        # partial leaves and tail children sort as coldest and evict
        # first under pressure
        if best_child is not None:
            best_child.tick = self._tick
        elif best_pidx is not None:
            ptoks, ppage, _ = node.partials[best_pidx]
            node.partials[best_pidx] = (ptoks, ppage, self._tick)
        cached = off + best_len
        if cached > 0:
            self._c_hits.inc()
            self._c_hit_tokens.inc(cached)
        else:
            self._c_misses.inc()
        partial = (best_page, best_len) if best_len else None
        return cached, pages, partial

    # ----------------------------------------------------------- insert
    def insert(self, tokens, pages) -> int:
        """Index a freshly prefilled session's prefix: `pages` is the
        session's page chain covering `tokens` (page i holds tokens
        [i*Lp, (i+1)*Lp)). Adopts pages by reference (the cache's own
        refcount); already-cached chunks are left alone — the donor
        keeps its private copy, both are valid. Returns the number of
        pages newly adopted. Caller holds the pool lock."""
        toks = tuple(int(t) for t in tokens)
        Lp = self.page_len
        self._tick += 1
        node, off, pi, adopted = self._root, 0, 0, 0
        while off + Lp <= len(toks) and pi < len(pages):
            chunk = toks[off:off + Lp]
            child = node.children.get(chunk)
            if child is None:
                # a partial leaf that this full chunk extends is now
                # redundant — the new page covers strictly more tokens
                # of the same content, so upgrade (drop the short one)
                keep = []
                for ptoks, ppage, ptick in node.partials:
                    if _lcp(ptoks, chunk) == len(ptoks):
                        self.pool.page_unref_locked(ppage)
                    else:
                        keep.append((ptoks, ppage, ptick))
                node.partials = keep
                child = _Node(pages[pi])
                self.pool.page_ref_locked(pages[pi])
                adopted += 1
                node.children[chunk] = child
            child.tick = self._tick
            node, off, pi = child, off + Lp, pi + 1
        tail = toks[off:]
        if tail and pi < len(pages):
            covered = any(_lcp(tail, e) == len(tail)
                          for e in node.children)
            best = None
            for idx, (ptoks, _, _) in enumerate(node.partials):
                k = _lcp(tail, ptoks)
                if k == len(ptoks) and len(tail) > len(ptoks):
                    best = idx          # existing is a proper prefix
                if k == len(tail):
                    covered = True      # tail already fully resident
            if best is not None and not covered:
                _, old_page, _ = node.partials[best]
                self.pool.page_unref_locked(old_page)
                self.pool.page_ref_locked(pages[pi])
                adopted += 1
                node.partials[best] = (tail, pages[pi], self._tick)
            elif not covered:
                self.pool.page_ref_locked(pages[pi])
                adopted += 1
                node.partials.append((tail, pages[pi], self._tick))
        if adopted:
            self._c_inserts.inc()
        return adopted

    def note_cow_fork(self) -> None:
        """Admission performed a copy-on-write fork of a partial page."""
        self._c_cow.inc()

    # --------------------------------------------------------- eviction
    def _evictable(self):
        """(tick, unref-thunk) for every leaf whose page only the cache
        still references. Interior nodes become eligible once their
        subtree is gone — the loop in evict() re-scans."""
        out = []

        def walk(node):
            for ptoks, ppage, ptick in node.partials:
                if self.pool.page_refcount_locked(ppage) == 1:
                    out.append((ptick, ("partial", node, (ptoks, ppage))))
            for edge, child in node.children.items():
                if not child.children and not child.partials:
                    if self.pool.page_refcount_locked(child.page) == 1:
                        out.append((child.tick, ("node", node, edge)))
                else:
                    walk(child)

        walk(self._root)
        return out

    def evict(self, need_pages: int) -> int:
        """Leaf-first LRU: release cache references on the coldest
        chains until `need_pages` pages have returned to the free list
        or nothing evictable remains. Only pages with pool refcount 1
        (cache-only) are touched — a live session's pages are
        untouchable by construction. Returns pages freed. Caller holds
        the pool lock."""
        freed = 0
        while freed < need_pages:
            cands = self._evictable()
            if not cands:
                break
            cands.sort(key=lambda c: c[0])
            progress = False
            for _, (kind, parent, key) in cands:
                if freed >= need_pages:
                    break
                if kind == "partial":
                    # the partials list mutates as entries pop, so the
                    # candidate is re-resolved by its (tokens, page)
                    # identity — a list index could name a DIFFERENT
                    # (hotter) partial after an earlier pop and violate
                    # LRU order
                    for i, (ptoks, ppage, _) in \
                            enumerate(parent.partials):
                        if (ptoks, ppage) != key:
                            continue
                        if self.pool.page_refcount_locked(ppage) == 1:
                            parent.partials.pop(i)
                            self.pool.page_unref_locked(ppage)
                            freed += 1
                            progress = True
                        break
                else:
                    child = parent.children.get(key)
                    if child is not None and not child.children \
                            and not child.partials:
                        del parent.children[key]
                        self.pool.page_unref_locked(child.page)
                        freed += 1
                        progress = True
            if not progress:
                break
        if freed:
            self._c_evicted.inc(freed)
        return freed

    def flush(self) -> int:
        """Drop every cached chain (hot-swap installed new weights: old
        KV is meaningless for NEW matches; live sessions keep their own
        references and finish on the pages they hold). Returns pages
        released. Caller holds the pool lock."""
        released = 0

        def walk(node):
            nonlocal released
            for _, ppage, _ in node.partials:
                self.pool.page_unref_locked(ppage)
                released += 1
            for child in node.children.values():
                walk(child)
                self.pool.page_unref_locked(child.page)
                released += 1

        walk(self._root)
        self._root = _Node()
        return released

    # ------------------------------------------------------ inspection
    def cached_pages(self) -> int:
        n = 0

        def walk(node):
            nonlocal n
            n += len(node.partials)
            for child in node.children.values():
                n += 1
                walk(child)

        walk(self._root)
        return n

    def stats(self) -> dict:
        hits = self._c_hits.value
        misses = self._c_misses.value
        lookups = hits + misses
        return {"hits": int(hits),
                "misses": int(misses),
                "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
                "hit_tokens": int(self._c_hit_tokens.value),
                "inserts": int(self._c_inserts.value),
                "cow_forks": int(self._c_cow.value),
                "evicted_pages": int(self._c_evicted.value),
                "cached_pages": self.cached_pages(),
                "page_len": self.page_len}
