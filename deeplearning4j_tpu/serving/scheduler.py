"""Continuous-batching scheduler + admission control for the serving
control plane.

Replaces the fixed collect-then-run loop (`ParallelInference._collector`,
which waits up to `max_wait_ms` hoping to fill a batch) with the
scheduling discipline real inference servers use: a request joins the
very next device dispatch as soon as a slot frees. While a slot is busy
the queue naturally accumulates arrivals, so batches grow under load and
shrink to singletons when idle — occupancy tracks load with no tuned
wait timer, which is exactly where the p99 win over collect-then-run
comes from (measured in `bench.py --serving`).

Admission control is a bounded queue with a configurable policy:

  block    — the submitting thread waits (bounded by `block_timeout_s`)
             for space; backpressure propagates to the HTTP client
  shed     — a full queue rejects immediately (`RequestShedError`,
             mapped to HTTP 503)
  deadline — every request carries a deadline (per-request or
             `default_deadline_ms`); admission waits only until the
             deadline (`DeadlineExceededError`, HTTP 504)

Deadlines propagate INTO the scheduler: a request that expires while
queued is failed and never dispatched — the accelerator never burns a
batch slot on work nobody is waiting for.

Shutdown contract (extends `parallel/inference.py`'s drain guarantee):
every submitted request either completes or fails with an explicit
error; nothing hangs. Queued requests are failed with
`SchedulerClosedError`; the batch in flight runs to completion.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.serving.metrics import ServingStats


class AdmissionPolicy:
    BLOCK = "block"
    SHED = "shed"
    DEADLINE = "deadline"

    ALL = (BLOCK, SHED, DEADLINE)


class RequestShedError(RuntimeError):
    """Admission queue full under the shed policy (HTTP 503)."""


class DeadlineExceededError(RuntimeError):
    """Request deadline passed before completion (HTTP 504)."""


class SchedulerClosedError(RuntimeError):
    """Scheduler shut down before (or while) holding this request."""


class _Request:
    __slots__ = ("x", "fut", "model", "deadline", "t_enqueue", "ctx",
                 "seq_key")

    def __init__(self, x, fut, model, deadline, ctx, seq_key):
        self.x = x
        self.fut = fut
        self.model = model
        self.deadline = deadline
        self.t_enqueue = time.monotonic()
        self.ctx = ctx
        self.seq_key = seq_key


class ContinuousBatchingScheduler:
    """Slot workers pulling per-model FIFO queues; one registry behind.

    `registry` needs `acquire(name) -> entry` / `release(entry)` with
    `entry.run_batch(xs)` (the ModelRegistry contract; unit tests pass
    fakes). `slots` is the number of concurrent device dispatch lanes —
    1 for a single mesh, >1 when the runner multiplexes devices.
    """

    def __init__(self, registry, stats: Optional[ServingStats] = None, *,
                 max_batch_size: int = 64, queue_capacity: int = 256,
                 policy: str = AdmissionPolicy.BLOCK,
                 default_deadline_ms: Optional[float] = None,
                 slots: int = 1, block_timeout_s: float = 30.0):
        if policy not in AdmissionPolicy.ALL:
            raise ValueError(
                f"admission policy must be one of {AdmissionPolicy.ALL}, "
                f"got {policy!r}")
        if policy == AdmissionPolicy.DEADLINE and not default_deadline_ms:
            raise ValueError(
                "deadline admission policy requires default_deadline_ms")
        self.registry = registry
        self.stats = stats if stats is not None else ServingStats()
        self.max_batch = max_batch_size
        self.capacity = queue_capacity
        self.policy = policy
        self.default_deadline = (default_deadline_ms / 1e3
                                 if default_deadline_ms else None)
        self.block_timeout = block_timeout_s
        self._cv = threading.Condition()
        self._queues: Dict[str, deque] = {}
        self._depth = 0
        self._inflight = 0
        self._closed = False
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"serving-slot-{i}")
            for i in range(max(1, slots))]
        for w in self._workers:
            w.start()

    # ---------------------------------------------------------- public
    def queue_depth(self) -> int:
        with self._cv:
            return self._depth

    def submit(self, model: str, x,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one request; returns a Future resolving to the output
        rows. Raises RequestShedError / DeadlineExceededError /
        SchedulerClosedError per the admission contract."""
        x = np.asarray(x)
        now = time.monotonic()
        dl_s = (deadline_ms / 1e3 if deadline_ms is not None
                else self.default_deadline)
        deadline = now + dl_s if dl_s is not None else None

        from deeplearning4j_tpu.parallel.ring_attention import (
            current_sequence_mesh,
        )

        with self._cv:
            if self._closed:
                raise SchedulerClosedError("scheduler is shut down")
            if self._depth >= self.capacity:
                if self.policy == AdmissionPolicy.SHED:
                    self.stats.shed(model)
                    raise RequestShedError(
                        f"admission queue full "
                        f"({self._depth}/{self.capacity})")
                limit = now + self.block_timeout
                if deadline is not None:
                    limit = min(limit, deadline)
                while self._depth >= self.capacity and not self._closed:
                    remaining = limit - time.monotonic()
                    if remaining <= 0:
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            self.stats.expired(model)
                            raise DeadlineExceededError(
                                "deadline passed waiting for admission")
                        self.stats.shed(model)
                        raise RequestShedError(
                            f"admission blocked > {self.block_timeout}s")
                    self._cv.wait(remaining)
                if self._closed:
                    raise SchedulerClosedError("scheduler is shut down")
            fut: Future = Future()
            req = _Request(x, fut, model, deadline,
                           contextvars.copy_context(),
                           current_sequence_mesh())
            self._queues.setdefault(model, deque()).append(req)
            self._depth += 1
            self.stats.admitted(model)
            self._cv.notify_all()
        return fut

    def output(self, model: str, x,
               deadline_ms: Optional[float] = None) -> np.ndarray:
        """Blocking submit; the synchronous convenience the HTTP handler
        uses."""
        return self.submit(model, x, deadline_ms).result()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty and no batch is in flight."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._depth == 0 and self._inflight == 0, timeout)

    def shutdown(self):
        """Fail everything queued with SchedulerClosedError, let the
        in-flight batch finish, stop the slot workers."""
        with self._cv:
            self._closed = True
            leftovers = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._depth = 0
            self._cv.notify_all()
        for r in leftovers:
            if not r.fut.done():
                r.fut.set_exception(SchedulerClosedError(
                    "scheduler shut down before serving this request"))
                self.stats.completed(r.model, 0.0, ok=False)
        for w in self._workers:
            w.join(timeout=10)

    # ---------------------------------------------------------- worker
    def _take_batch(self):
        """Pop the next single-(model, seq-context) batch, FIFO-fair
        across models by oldest head request. Called under self._cv."""
        name = min((n for n, q in self._queues.items() if q),
                   key=lambda n: self._queues[n][0].t_enqueue)
        q = self._queues[name]
        batch = [q.popleft()]
        rows = batch[0].x.shape[0]
        while (q and rows < self.max_batch
               and q[0].seq_key == batch[0].seq_key):
            nxt = q.popleft()
            batch.append(nxt)
            rows += nxt.x.shape[0]
        # graft: allow(GL301): caller holds self._cv (documented contract)
        self._depth -= len(batch)
        return batch

    def _worker(self):
        try:
            while True:
                with self._cv:
                    while not self._closed and self._depth == 0:
                        self._cv.wait()
                    if self._closed:
                        return
                    batch = self._take_batch()
                    self._inflight += 1
                    self._cv.notify_all()   # wake admission waiters
                try:
                    self._dispatch(batch)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()
        except BaseException as e:
            # a dead worker thread is a silent serving outage (daemon
            # threads die without a traceback anyone keeps): leave the
            # black box before propagating
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().dump("scheduler_worker_crash", exc=e)
            # graft: allow(GL403): the dump is best-effort forensics;
            # the original worker crash must propagate unmasked
            except Exception:
                pass
            raise

    def _dispatch(self, batch):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now >= r.deadline:
                # expired while queued: never ship it to the device
                self.stats.expired(r.model)
                if not r.fut.done():
                    r.fut.set_exception(DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{now - r.t_enqueue:.3f}s in queue"))
                continue
            live.append(r)
        if not live:
            return
        model = live[0].model
        try:
            entry = self.registry.acquire(model)
        except BaseException as e:
            for r in live:
                if not r.fut.done():
                    r.fut.set_exception(e)
                self.stats.completed(r.model, 0.0, ok=False)
            return
        try:
            xs = (live[0].x if len(live) == 1
                  else np.concatenate([r.x for r in live], axis=0))
            self.stats.batch_dispatched(xs.shape[0], self.max_batch)
            ys = live[0].ctx.run(entry.run_batch, xs)
            done = time.monotonic()
            ver = getattr(entry, "version", None)
            off = 0
            for r in live:
                n = r.x.shape[0]
                if not r.fut.done():
                    # stamp which deployed version served this request
                    # BEFORE resolving, so result() readers see it —
                    # the hot-swap zero-downtime evidence
                    r.fut.version = ver
                    r.fut.set_result(ys[off:off + n])
                self.stats.completed(r.model, done - r.t_enqueue)
                off += n
        except BaseException as e:
            for r in live:
                if not r.fut.done():
                    r.fut.set_exception(e)
                self.stats.completed(r.model, 0.0, ok=False)
            # per-batch faults surface through futures and stats; a ring
            # breadcrumb keeps them visible in a later crash dump too
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().record("serving_dispatch_error", model=model,
                                    error=type(e).__name__,
                                    requests=len(live))
            # graft: allow(GL403): ring breadcrumb is best-effort; the
            # fault already reached every future and the stats above
            except Exception:
                pass
        finally:
            self.registry.release(entry)
