"""Continuous-batching scheduler + admission control for the serving
control plane.

Replaces the fixed collect-then-run loop (`ParallelInference._collector`,
which waits up to `max_wait_ms` hoping to fill a batch) with the
scheduling discipline real inference servers use: a request joins the
very next device dispatch as soon as a slot frees. While a slot is busy
the queue naturally accumulates arrivals, so batches grow under load and
shrink to singletons when idle — occupancy tracks load with no tuned
wait timer, which is exactly where the p99 win over collect-then-run
comes from (measured in `bench.py --serving`).

Admission control is a bounded queue with a configurable policy:

  block    — the submitting thread waits (bounded by `block_timeout_s`)
             for space; backpressure propagates to the HTTP client
  shed     — a full queue rejects immediately (`RequestShedError`,
             mapped to HTTP 503)
  deadline — every request carries a deadline (per-request or
             `default_deadline_ms`); admission waits only until the
             deadline (`DeadlineExceededError`, HTTP 504)

Deadlines propagate INTO the scheduler: a request that expires while
queued is failed and never dispatched — the accelerator never burns a
batch slot on work nobody is waiting for.

Shutdown contract (extends `parallel/inference.py`'s drain guarantee):
every submitted request either completes or fails with an explicit
error; nothing hangs. Queued requests are failed with
`SchedulerClosedError`; the batch in flight runs to completion.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.observe import reqtrace
from deeplearning4j_tpu.serving.metrics import ServingStats


class AdmissionPolicy:
    BLOCK = "block"
    SHED = "shed"
    DEADLINE = "deadline"

    ALL = (BLOCK, SHED, DEADLINE)


class RequestShedError(RuntimeError):
    """Admission queue full under the shed policy (HTTP 503)."""


class DeadlineExceededError(RuntimeError):
    """Request deadline passed before completion (HTTP 504)."""


class SchedulerClosedError(RuntimeError):
    """Scheduler shut down before (or while) holding this request."""


class WorkerCrashError(RuntimeError):
    """A slot worker crashed more than `max_worker_restarts` times in a
    row while holding this batch; the batch was failed rather than
    retried forever. The slot itself stays alive for new work."""


class _WorkerCrashed(BaseException):
    """Internal: carries the in-flight batch out of a crashed worker
    iteration to the supervisor (BaseException so nothing downstream
    accidentally swallows it)."""

    def __init__(self, batch, cause: BaseException):
        super().__init__(str(cause))
        self.batch = batch
        self.cause = cause


class _Request:
    __slots__ = ("x", "fut", "model", "deadline", "t_enqueue", "ctx",
                 "seq_key", "trace", "t_wall")

    def __init__(self, x, fut, model, deadline, ctx, seq_key, trace=None):
        self.x = x
        self.fut = fut
        self.model = model
        self.deadline = deadline
        self.t_enqueue = time.monotonic()
        self.ctx = ctx
        self.seq_key = seq_key
        # request-trace seam: None on the sampled-off fast path (no span
        # objects allocated); t_wall anchors the queue.wait span
        self.trace = trace
        self.t_wall = time.time() if trace is not None else 0.0


class ContinuousBatchingScheduler:
    """Slot workers pulling per-model FIFO queues; one registry behind.

    `registry` needs `acquire(name) -> entry` / `release(entry)` with
    `entry.run_batch(xs)` (the ModelRegistry contract; unit tests pass
    fakes). `slots` is the number of concurrent device dispatch lanes —
    1 for a single mesh, >1 when the runner multiplexes devices.
    """

    def __init__(self, registry, stats: Optional[ServingStats] = None, *,
                 max_batch_size: int = 64, queue_capacity: int = 256,
                 policy: str = AdmissionPolicy.BLOCK,
                 default_deadline_ms: Optional[float] = None,
                 slots: int = 1, block_timeout_s: float = 30.0,
                 max_worker_restarts: int = 3,
                 worker_restart_backoff_s: float = 0.05):
        if policy not in AdmissionPolicy.ALL:
            raise ValueError(
                f"admission policy must be one of {AdmissionPolicy.ALL}, "
                f"got {policy!r}")
        if policy == AdmissionPolicy.DEADLINE and not default_deadline_ms:
            raise ValueError(
                "deadline admission policy requires default_deadline_ms")
        self.registry = registry
        self.stats = stats if stats is not None else ServingStats()
        self.max_batch = max_batch_size
        self.capacity = queue_capacity
        self.policy = policy
        self.default_deadline = (default_deadline_ms / 1e3
                                 if default_deadline_ms else None)
        self.block_timeout = block_timeout_s
        # worker supervision: a crashed slot restarts with doubling
        # backoff; after max_worker_restarts consecutive crashes the held
        # batch is failed (WorkerCrashError) instead of retried forever
        self.max_worker_restarts = max(0, int(max_worker_restarts))
        self.worker_restart_backoff = float(worker_restart_backoff_s)
        self._cv = threading.Condition()
        # queue state is mutated by submitters and worker threads alike;
        # declared guards let graft-lint (GL701) verify every access —
        # helpers like _take_batch stay quiet because their only call
        # sites hold self._cv (interprocedural entry-held propagation)
        # graft: guarded-by(_cv)
        self._queues: Dict[str, deque] = {}
        # graft: guarded-by(_cv)
        self._depth = 0
        # graft: guarded-by(_cv)
        self._inflight = 0
        # graft: guarded-by(_cv)
        self._closed = False
        # per-worker CURRENT crash streaks (worker thread name → count);
        # restart_streak() reads the worst one for /healthz and the SLO
        self._streaks: Dict[str, int] = {}
        # chaos seam (inject_worker_fault): raise in the next N worker
        # iterations right after a batch is taken — guarded by self._cv
        self._fault_budget = 0
        self._fault_exc = None
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"serving-slot-{i}")
            for i in range(max(1, slots))]
        for w in self._workers:
            w.start()

    # ---------------------------------------------------------- public
    def queue_depth(self) -> int:
        with self._cv:
            return self._depth

    def restart_streak(self) -> int:
        """Worst current consecutive-crash streak across slot workers
        (0 = healthy). Nonzero means a slot is crash-looping RIGHT NOW —
        a healthy dispatch resets its worker's streak."""
        with self._cv:
            return max(self._streaks.values(), default=0)

    def _note_streak(self, n: int) -> None:
        with self._cv:
            self._streaks[threading.current_thread().name] = n
            worst = max(self._streaks.values())
        self.stats.worker_streak(worst)

    def submit(self, model: str, x,
               deadline_ms: Optional[float] = None, *,
               trace=None) -> Future:
        """Admit one request; returns a Future resolving to the output
        rows. Raises RequestShedError / DeadlineExceededError /
        SchedulerClosedError per the admission contract.

        `trace` carries the request's TraceContext across the admission
        seam (decode sessions resubmit from scheduler worker threads, so
        the contextvar carrier alone is not enough); when omitted, the
        edge's `reqtrace.current_trace()` is picked up. Shed / expired
        requests are force-traced regardless of the sampling rate and
        the trace id is stamped on the raised exception."""
        x = np.asarray(x)
        now = time.monotonic()
        dl_s = (deadline_ms / 1e3 if deadline_ms is not None
                else self.default_deadline)
        deadline = now + dl_s if dl_s is not None else None
        if trace is None:
            trace = reqtrace.current_trace()

        from deeplearning4j_tpu.parallel.ring_attention import (
            current_sequence_mesh,
        )

        with self._cv:
            if self._closed:
                raise SchedulerClosedError("scheduler is shut down")
            if self._depth >= self.capacity:
                if self.policy == AdmissionPolicy.SHED:
                    self.stats.shed(model)
                    err = RequestShedError(
                        f"admission queue full "
                        f"({self._depth}/{self.capacity})")
                    err.trace_id = reqtrace.error_trace(
                        "request.shed", ctx=trace, model=model,
                        queue_depth=self._depth, capacity=self.capacity)
                    raise err
                limit = now + self.block_timeout
                if deadline is not None:
                    limit = min(limit, deadline)
                while self._depth >= self.capacity and not self._closed:
                    remaining = limit - time.monotonic()
                    if remaining <= 0:
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            self.stats.expired(model)
                            err = DeadlineExceededError(
                                "deadline passed waiting for admission")
                            err.trace_id = reqtrace.error_trace(
                                "request.expired", ctx=trace, model=model,
                                where="admission")
                            raise err
                        self.stats.shed(model)
                        err = RequestShedError(
                            f"admission blocked > {self.block_timeout}s")
                        err.trace_id = reqtrace.error_trace(
                            "request.shed", ctx=trace, model=model,
                            queue_depth=self._depth,
                            blocked_s=round(self.block_timeout, 3))
                        raise err
                    self._cv.wait(remaining)
                if self._closed:
                    raise SchedulerClosedError("scheduler is shut down")
            fut: Future = Future()
            req = _Request(x, fut, model, deadline,
                           contextvars.copy_context(),
                           current_sequence_mesh(), trace)
            self._queues.setdefault(model, deque()).append(req)
            self._depth += 1
            self.stats.admitted(model)
            self._cv.notify_all()
        return fut

    def output(self, model: str, x,
               deadline_ms: Optional[float] = None) -> np.ndarray:
        """Blocking submit; the synchronous convenience the HTTP handler
        uses."""
        return self.submit(model, x, deadline_ms).result()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty and no batch is in flight."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._depth == 0 and self._inflight == 0, timeout)

    def shutdown(self):
        """Fail everything queued with SchedulerClosedError, let the
        in-flight batch finish, stop the slot workers."""
        with self._cv:
            self._closed = True
            leftovers = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._depth = 0
            self._cv.notify_all()
        for r in leftovers:
            if not r.fut.done():
                r.fut.set_exception(SchedulerClosedError(
                    "scheduler shut down before serving this request"))
                self.stats.completed(r.model, 0.0, ok=False)
        for w in self._workers:
            w.join(timeout=10)

    # ---------------------------------------------------------- worker
    def _take_batch(self):
        """Pop the next single-(model, seq-context) batch, FIFO-fair
        across models by oldest head request. Called under self._cv."""
        name = min((n for n, q in self._queues.items() if q),
                   key=lambda n: self._queues[n][0].t_enqueue)
        q = self._queues[name]
        batch = [q.popleft()]
        rows = batch[0].x.shape[0]
        while (q and rows < self.max_batch
               and q[0].seq_key == batch[0].seq_key):
            nxt = q.popleft()
            batch.append(nxt)
            rows += nxt.x.shape[0]
        # graft: allow(GL301): caller holds self._cv (documented contract)
        self._depth -= len(batch)
        return batch

    def inject_worker_fault(self, *, times: int = 1,
                            exc_factory=None) -> None:
        """Chaos seam: make the next `times` worker iterations crash
        right after taking a batch — the thread-death scenario the
        supervisor exists for, injectable deterministically on CPU
        (tests/test_serving_failover)."""
        from deeplearning4j_tpu.parallel.chaos import InjectedFault
        with self._cv:
            self._fault_budget = int(times)
            self._fault_exc = exc_factory or (
                lambda: InjectedFault("injected worker crash"))

    def _worker(self):
        """Supervisor: before ISSUE 6 a crash here killed the daemon
        thread silently and the slot went dark — every later request
        hung until its deadline. Now the slot survives: the held batch
        is requeued at the FRONT (order preserved), the crash is
        flight-dumped and counted (`serving_worker_restarts_total`), and
        the loop restarts after a doubling backoff. A crash LOOP is
        bounded: after `max_worker_restarts` consecutive crashes the
        held batch fails with WorkerCrashError and the slot moves on."""
        streak = [0]               # consecutive crashes; dispatch resets
        backoff = self.worker_restart_backoff
        while True:
            try:
                self._worker_loop(streak)
                return             # clean shutdown
            except _WorkerCrashed as wc:
                batch, cause = wc.batch, wc.cause
            streak[0] += 1
            self._note_streak(streak[0])
            self.stats.worker_restarted()
            # a dead worker thread is a silent serving outage (daemon
            # threads die without a traceback anyone keeps): black box
            # first, then recover
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().dump("scheduler_worker_crash", exc=cause)
            # graft: allow(GL403): the dump is best-effort forensics;
            # the restart below is the payload
            except Exception:
                pass
            if streak[0] > self.max_worker_restarts:
                for r in batch:
                    exc = WorkerCrashError(
                        f"worker crashed {streak[0]} consecutive "
                        f"times holding this batch: {cause!r}")
                    exc.trace_id = reqtrace.error_trace(
                        "request.worker_crash", ctx=r.trace,
                        model=r.model, crashes=streak[0],
                        cause=type(cause).__name__)
                    if not r.fut.done():
                        r.fut.set_exception(exc)
                    self.stats.completed(r.model, 0.0, ok=False)
                streak[0] = 0
                self._note_streak(0)
                backoff = self.worker_restart_backoff
                continue
            if batch:
                self._requeue(batch)
            time.sleep(backoff)
            backoff = min(backoff * 2.0, 1.0)

    def _requeue(self, batch) -> None:
        """Put a crashed worker's batch back at the head of its queue
        (oldest request first, so FIFO order survives the restart)."""
        with self._cv:
            if self._closed:
                closed = list(batch)
            else:
                closed = []
                q = self._queues.setdefault(batch[0].model, deque())
                for r in reversed(batch):
                    q.appendleft(r)
                self._depth += len(batch)
            self._cv.notify_all()
        for r in closed:        # raced shutdown: fail, don't strand
            if not r.fut.done():
                r.fut.set_exception(SchedulerClosedError(
                    "scheduler shut down while recovering this request"))
            self.stats.completed(r.model, 0.0, ok=False)

    def _worker_loop(self, streak):
        while True:
            try:
                with self._cv:
                    while not self._closed and self._depth == 0:
                        self._cv.wait()
                    if self._closed:
                        return
                    batch = self._take_batch()
                    self._inflight += 1
                    if self._fault_budget > 0:
                        self._fault_budget -= 1
                        fault = self._fault_exc()
                    else:
                        fault = None
                    self._cv.notify_all()   # wake admission waiters
            except BaseException as e:
                # a crash in the take phase holds no batch yet; it still
                # must reach the supervisor, not kill the thread
                raise _WorkerCrashed([], e) from e
            try:
                if fault is not None:
                    raise fault
                self._dispatch(batch)
                if streak[0]:          # healthy dispatch ends the streak
                    streak[0] = 0
                    self._note_streak(0)
            except _WorkerCrashed:
                raise
            except BaseException as e:
                raise _WorkerCrashed(batch, e) from e
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _dispatch(self, batch):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now >= r.deadline:
                # expired while queued: never ship it to the device
                self.stats.expired(r.model)
                exc = DeadlineExceededError(
                    f"deadline exceeded after "
                    f"{now - r.t_enqueue:.3f}s in queue")
                exc.trace_id = reqtrace.error_trace(
                    "request.expired", ctx=r.trace, model=r.model,
                    where="queue", queue_s=round(now - r.t_enqueue, 3))
                if not r.fut.done():
                    r.fut.set_exception(exc)
                continue
            live.append(r)
        if not live:
            return
        model = live[0].model
        for r in live:
            # queue wait = admission → dispatch; one histogram observe
            # per request (same cost class as completed() below)
            self.stats.queue_waited(r.model, (now - r.t_enqueue) * 1e3)
        try:
            entry = self.registry.acquire(model)
        except BaseException as e:
            for r in live:
                if not r.fut.done():
                    r.fut.set_exception(e)
                self.stats.completed(r.model, 0.0, ok=False)
            return
        dt = None
        try:
            xs = (live[0].x if len(live) == 1
                  else np.concatenate([r.x for r in live], axis=0))
            self.stats.batch_dispatched(xs.shape[0], self.max_batch)
            traced = [r for r in live if r.trace is not None]
            if traced:
                # fan-in seam: close each trace's admission wait, then
                # open ONE dispatch window joining all co-batched traces
                # (begin_dispatch pins it to this worker thread so
                # run_batch can parent per-row session-step spans on it)
                t_w = time.time()
                for r in traced:
                    reqtrace.record_span(
                        r.trace.trace_id, "queue.wait",
                        parent_id=r.trace.span_id, ts=r.t_wall,
                        dur_ms=(t_w - r.t_wall) * 1e3, model=model)
                dt = reqtrace.begin_dispatch([r.trace for r in traced])
            ys = live[0].ctx.run(entry.run_batch, xs)
            done = time.monotonic()
            ver = getattr(entry, "version", None)
            reqtrace.end_dispatch(dt, model=model, rows=int(xs.shape[0]),
                                  requests=len(live), version=ver)
            dt = None
            off = 0
            for r in live:
                n = r.x.shape[0]
                if not r.fut.done():
                    # stamp which deployed version served this request
                    # BEFORE resolving, so result() readers see it —
                    # the hot-swap zero-downtime evidence
                    r.fut.version = ver
                    r.fut.set_result(ys[off:off + n])
                self.stats.completed(
                    r.model, done - r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace else None)
                off += n
        except BaseException as e:
            reqtrace.end_dispatch(dt, model=model, requests=len(live),
                                  error=type(e).__name__)
            for r in live:
                if not r.fut.done():
                    r.fut.set_exception(e)
                self.stats.completed(r.model, 0.0, ok=False)
            # per-batch faults surface through futures and stats; a ring
            # breadcrumb keeps them visible in a later crash dump too
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().record("serving_dispatch_error", model=model,
                                    error=type(e).__name__,
                                    requests=len(live))
            # graft: allow(GL403): ring breadcrumb is best-effort; the
            # fault already reached every future and the stats above
            except Exception:
                pass
        finally:
            self.registry.release(entry)
