"""ServingStats — lock-cheap observability aggregator for the serving
control plane.

Backs the server's `/metrics` endpoint. All hot-path hooks (`admitted`,
`completed`, `batch_dispatched`, `shed`, `expired`) take one short
`threading.Lock` acquisition around a handful of counter bumps and a
bounded-deque append — no allocation proportional to traffic, no
percentile math on the request path. Percentiles and the occupancy
histogram are computed on demand in `snapshot()` (the /metrics reader
pays, not the request).

Reference precedent: the reference's `PerformanceListener` /
`BenchmarkDataSetIterator` measurement seams, lifted from the training
loop onto the serving path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

# occupancy histogram bucket upper bounds (fraction of max_batch filled)
OCCUPANCY_EDGES = (0.125, 0.25, 0.5, 0.75, 1.0)


class _ModelStats:
    __slots__ = ("admitted", "completed", "failed", "shed", "expired",
                 "latencies")

    def __init__(self, window: int):
        self.admitted = 0
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.expired = 0
        self.latencies: deque = deque(maxlen=window)


class ServingStats:
    """Per-model request counters + rolling latency window + global
    batch-occupancy histogram."""

    def __init__(self, *, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._window = latency_window
        self._models: Dict[str, _ModelStats] = {}
        self._occupancy = [0] * (len(OCCUPANCY_EDGES) + 1)
        self._batches = 0
        self._batch_rows = 0
        self._started = time.time()

    def _m(self, model: str) -> _ModelStats:
        s = self._models.get(model)
        if s is None:
            s = self._models[model] = _ModelStats(self._window)
        return s

    # ------------------------------------------------------- hot hooks
    def admitted(self, model: str):
        with self._lock:
            self._m(model).admitted += 1

    def shed(self, model: str):
        with self._lock:
            self._m(model).shed += 1

    def expired(self, model: str):
        with self._lock:
            self._m(model).expired += 1

    def completed(self, model: str, latency_s: float, ok: bool = True):
        with self._lock:
            s = self._m(model)
            if ok:
                s.completed += 1
                s.latencies.append(latency_s)
            else:
                s.failed += 1

    def batch_dispatched(self, rows: int, capacity: int):
        """One device dispatch of `rows` rows against a `capacity`-row
        budget; buckets the fill fraction into the occupancy histogram."""
        frac = rows / capacity if capacity else 1.0
        i = 0
        while i < len(OCCUPANCY_EDGES) and frac > OCCUPANCY_EDGES[i]:
            i += 1
        with self._lock:
            self._occupancy[i] += 1
            self._batches += 1
            self._batch_rows += rows

    # ------------------------------------------------------- reporting
    @staticmethod
    def _percentiles(sorted_lat):
        if not sorted_lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        n = len(sorted_lat)

        def pick(q):
            return round(sorted_lat[min(n - 1, int(q * n))] * 1e3, 3)

        return {"p50_ms": pick(0.50), "p95_ms": pick(0.95),
                "p99_ms": pick(0.99)}

    def snapshot(self, *, queue_depth: Optional[int] = None,
                 queue_capacity: Optional[int] = None) -> dict:
        """The /metrics payload. Queue gauges are passed in by the owner
        (the scheduler holds them; this aggregator only holds counters)."""
        with self._lock:
            models = {
                name: {
                    "admitted": s.admitted,
                    "completed": s.completed,
                    "failed": s.failed,
                    "shed": s.shed,
                    "expired": s.expired,
                    "latency": dict(window=len(s.latencies),
                                    **self._percentiles(sorted(s.latencies))),
                } for name, s in self._models.items()}
            occupancy = list(self._occupancy)
            batches, rows = self._batches, self._batch_rows
            all_lat = sorted(
                v for s in self._models.values() for v in s.latencies)
        labels = ["<=12.5%", "<=25%", "<=50%", "<=75%", "<=100%", ">100%"]
        out = {
            "uptime_s": round(time.time() - self._started, 1),
            "requests": {
                k: sum(m[k] for m in models.values())
                for k in ("admitted", "completed", "failed", "shed",
                          "expired")},
            "latency": dict(window=len(all_lat),
                            **self._percentiles(all_lat)),
            "batch": {
                "dispatches": batches,
                "rows": rows,
                "mean_occupancy_rows": round(rows / batches, 3)
                if batches else None,
                "occupancy_histogram": dict(zip(labels, occupancy)),
            },
            "per_model": models,
        }
        if queue_depth is not None:
            out["queue"] = {"depth": queue_depth,
                            "capacity": queue_capacity}
        return out
