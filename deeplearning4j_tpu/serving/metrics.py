"""ServingStats — the serving-side renderer over the shared
`observe.MetricsRegistry`.

Formerly a private aggregator with its own locks and deques; now every
count lives in a `MetricsRegistry` (by default a private one per server
for isolation, or pass the process-wide `observe.get_registry()` so the
serving `/metrics` endpoint and the training listeners share ONE
telemetry spine — the unified-observability contract). `snapshot()`
keeps the exact JSON schema the control-plane tests pin; the Prometheus
rendering of the same registry is served by the HTTP endpoint when the
scraper asks for `text/plain` (exposition format 0.0.4).

Hot-path pricing is unchanged: each hook is a couple of short
lock-guarded bumps on cached instrument handles — no allocation
proportional to traffic, no percentile math on the request path
(readers pay in `snapshot()`, as before).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.observe.registry import MetricsRegistry

# occupancy histogram bucket upper bounds (fraction of max_batch filled)
OCCUPANCY_EDGES = (0.125, 0.25, 0.5, 0.75, 1.0)
_OCC_LABELS = ("<=12.5%", "<=25%", "<=50%", "<=75%", "<=100%", ">100%")

_OUTCOMES = ("admitted", "completed", "failed", "shed", "expired")


class _ModelSeries:
    """Cached instrument handles for one model's series."""

    __slots__ = ("outcomes", "latency", "queue_wait")

    def __init__(self, registry: MetricsRegistry, model: str, window: int):
        self.outcomes = {
            k: registry.counter("serving_requests_total",
                                model=model, outcome=k)
            for k in _OUTCOMES}
        self.latency = registry.histogram(
            "serving_latency_seconds", reservoir=window, model=model)
        self.queue_wait = registry.histogram(
            "serving_queue_wait_ms", reservoir=window, model=model)


class ServingStats:
    """Per-model request counters + rolling latency window + global
    batch-occupancy histogram, recorded into a MetricsRegistry."""

    def __init__(self, *, latency_window: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else \
            MetricsRegistry(reservoir=latency_window)
        self._window = latency_window
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelSeries] = {}
        self._occupancy = [
            self.registry.counter("serving_batch_occupancy_total",
                                  bucket=lab) for lab in _OCC_LABELS]
        self._dispatches = self.registry.counter(
            "serving_batch_dispatches_total")
        self._rows = self.registry.counter("serving_batch_rows_total")
        self._q_depth = self.registry.gauge("serving_queue_depth")
        self._q_cap = self.registry.gauge("serving_queue_capacity")
        self._worker_restarts = self.registry.counter(
            "serving_worker_restarts_total")
        # worst CURRENT consecutive-crash streak across slot workers —
        # nonzero means a slot is crash-looping right now (the restarts
        # counter above only says it happened at some point)
        self._worker_streak = self.registry.gauge(
            "serving_worker_restart_streak")
        self._started = time.time()
        self.registry.gauge("serving_start_time_seconds").set(self._started)

    def _m(self, model: str) -> _ModelSeries:
        # graft: allow(GL701): double-checked fast path — model keys are
        # never deleted, so a lock-free hit returns a stable object; the
        # miss path re-checks under the lock before inserting
        s = self._models.get(model)
        if s is None:
            with self._lock:
                s = self._models.get(model)
                if s is None:
                    s = self._models[model] = _ModelSeries(
                        self.registry, model, self._window)
        return s

    # ------------------------------------------------------- hot hooks
    def admitted(self, model: str):
        self._m(model).outcomes["admitted"].inc()

    def shed(self, model: str):
        self._m(model).outcomes["shed"].inc()

    def expired(self, model: str):
        self._m(model).outcomes["expired"].inc()

    def completed(self, model: str, latency_s: float, ok: bool = True,
                  trace_id: Optional[str] = None):
        s = self._m(model)
        if ok:
            s.outcomes["completed"].inc()
            # sampled requests stamp an exemplar so a tail latency in
            # /metrics links back to its trace tree (GET /trace/{id})
            s.latency.observe(latency_s, exemplar=trace_id)
        else:
            s.outcomes["failed"].inc()

    def batch_dispatched(self, rows: int, capacity: int):
        """One device dispatch of `rows` rows against a `capacity`-row
        budget; buckets the fill fraction into the occupancy histogram."""
        frac = rows / capacity if capacity else 1.0
        i = 0
        while i < len(OCCUPANCY_EDGES) and frac > OCCUPANCY_EDGES[i]:
            i += 1
        self._occupancy[i].inc()
        self._dispatches.inc()
        self._rows.inc(rows)

    def queue_waited(self, model: str, wait_ms: float):
        """Admission-to-dispatch queue wait for one request — the
        series the queue-wait SLO watches."""
        self._m(model).queue_wait.observe(wait_ms)

    def worker_restarted(self):
        """One supervised slot-worker restart after a crash — nonzero
        here means the scheduler survived something that used to be a
        silent outage (a dead daemon thread)."""
        self._worker_restarts.inc()

    def worker_streak(self, streak: int):
        """Worst current consecutive-crash streak (0 = all slots
        healthy); feeds the restart-streak SLO and /healthz."""
        self._worker_streak.set(streak)

    def set_queue_gauges(self, depth: Optional[int],
                         capacity: Optional[int]) -> None:
        """Push the scheduler-owned queue gauges into the registry so the
        Prometheus rendering carries them (the JSON snapshot takes them
        as arguments, as before)."""
        if depth is not None:
            self._q_depth.set(depth)
        if capacity is not None:
            self._q_cap.set(capacity)

    # ------------------------------------------------------- reporting
    @staticmethod
    def _percentiles(sorted_lat):
        if not sorted_lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        n = len(sorted_lat)

        def pick(q):
            return round(sorted_lat[min(n - 1, int(q * n))] * 1e3, 3)

        return {"p50_ms": pick(0.50), "p95_ms": pick(0.95),
                "p99_ms": pick(0.99)}

    def snapshot(self, *, queue_depth: Optional[int] = None,
                 queue_capacity: Optional[int] = None) -> dict:
        """The JSON /metrics payload. Queue gauges are passed in by the
        owner (the scheduler holds them; this renderer only holds
        counters)."""
        with self._lock:
            model_series = dict(self._models)
        models = {}
        all_lat = []
        for name, s in model_series.items():
            lat = s.latency.values()
            all_lat.extend(lat)
            models[name] = {
                **{k: int(c.value) for k, c in s.outcomes.items()},
                "latency": dict(window=len(lat),
                                **self._percentiles(sorted(lat))),
            }
        all_lat.sort()
        dispatches = int(self._dispatches.value)
        rows = int(self._rows.value)
        out = {
            "uptime_s": round(time.time() - self._started, 1),
            "requests": {
                k: sum(m[k] for m in models.values()) for k in _OUTCOMES},
            "latency": dict(window=len(all_lat),
                            **self._percentiles(all_lat)),
            "batch": {
                "dispatches": dispatches,
                "rows": rows,
                "mean_occupancy_rows": round(rows / dispatches, 3)
                if dispatches else None,
                "occupancy_histogram": {
                    lab: int(c.value)
                    for lab, c in zip(_OCC_LABELS, self._occupancy)},
            },
            "per_model": models,
            "workers": {"restarts": int(self._worker_restarts.value)},
        }
        if queue_depth is not None:
            out["queue"] = {"depth": queue_depth,
                            "capacity": queue_capacity}
            self.set_queue_gauges(queue_depth, queue_capacity)
        return out
