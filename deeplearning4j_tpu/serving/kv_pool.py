"""KVSlotPool — a paged allocator over batched, slot-indexed decode
carries.

The pool owns ONE device-resident carry tree built by
`net.session_carries(slots)`: every attention layer's KV cache is
[slots, L, Hkv, Dh] with a per-slot position vector, every recurrent
layer's h/c is [slots, n]. A slot (one batch row across the whole tree)
is the unit of admission for decode sessions: `alloc()` hands a free row
to a new session, `free()` zeroes it and returns it. Nothing here ever
retraces — allocation is host bookkeeping, and the reset is a single
jitted program whose slot index is a traced scalar, so session churn
costs zero compiles (the fixed-shape decode contract the recompile
watchdog polices).

Against cross-session leakage the pool is belt-and-braces: the rolling
ring's held-position arithmetic already makes a fresh slot's stale rows
invisible (a reset position of 0 puts every old slot entry on a previous
lap, `held < 0`), AND `free()` zeroes the slot's rows anyway so a bug in
either layer cannot expose the previous session's keys/values. The
wraparound-reuse test pins both.

Occupancy rides the shared metrics spine: `serving_kv_slots` /
`serving_kv_slots_in_use` gauges plus alloc/reset counters.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp


class SlotPoolExhaustedError(RuntimeError):
    """No free KV slot (HTTP 503 — admission is slots, not queue depth)."""


class IncompatibleSessionSwapError(RuntimeError):
    """A deploy candidate's session-carry tree (shapes/dtypes/structure)
    does not match the live pool — live sessions cannot migrate onto it,
    so the deploy must roll back rather than drop them."""


class KVSlotPool:
    """Slot-indexed decode carries + free-list allocation + jitted
    per-slot reset."""

    def __init__(self, net, slots: int, *, model: str = "default",
                 metrics=None, kv_dtype: Optional[str] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.net = net
        self.slots = int(slots)
        self.model = model
        self.kv_dtype = kv_dtype or "native"
        self._cv = threading.Condition()
        # the decode carry pytree and slot occupancy are the shared
        # state every request thread contends on; declare the guard so
        # graft-lint's interprocedural pass (GL701) checks every reader
        # — callers that enter via `with pool.lock():` stay quiet
        # graft: guarded-by(_cv)
        self.carries = net.session_carries(self.slots, kv_dtype=kv_dtype)
        # graft: guarded-by(_cv)
        self._free = list(range(self.slots - 1, -1, -1))
        # graft: guarded-by(_cv)
        self._active = [False] * self.slots

        def _reset(carries, slot):
            def z(a):
                # graft: allow(GL003): ndim/shape are static array
                # metadata, constant per trace — not traced values
                if getattr(a, "ndim", 0) >= 1 and a.shape[0] == slots:
                    return a.at[slot].set(jnp.zeros_like(a[slot]))
                return a
            return jax.tree_util.tree_map(z, carries)

        # slot is a traced scalar: one compile covers every reset ever
        self._reset_jit = jax.jit(_reset)

        if metrics is None:
            from deeplearning4j_tpu.observe import get_registry
            metrics = get_registry()
        self._g_total = metrics.gauge("serving_kv_slots", model=model)
        self._g_used = metrics.gauge("serving_kv_slots_in_use", model=model)
        self._c_allocs = metrics.counter("serving_kv_slot_allocs_total",
                                         model=model)
        self._c_resets = metrics.counter("serving_kv_slot_resets_total",
                                         model=model)
        self._g_total.set(self.slots)
        self._g_used.set(0)

    def lock(self):
        """The pool lock, for the step critical section: the dispatch
        path holds it across read-carries -> session_step -> writeback so
        concurrent decode dispatches serialize on the one carry tree."""
        return self._cv

    # ------------------------------------------------------- allocation
    def alloc(self, timeout_s: float = 0.0) -> int:
        """Claim a free slot; raises SlotPoolExhaustedError when none
        frees within `timeout_s` (0 = fail fast; admission pressure maps
        to HTTP 503, not an unbounded queue)."""
        with self._cv:
            if not self._free and timeout_s > 0:
                self._cv.wait_for(lambda: bool(self._free), timeout_s)
            if not self._free:
                raise SlotPoolExhaustedError(
                    f"all {self.slots} KV slots in use")
            slot = self._free.pop()
            self._active[slot] = True
            self._c_allocs.inc()
            self._g_used.set(self.slots - len(self._free))
            return slot

    def free(self, slot: int) -> None:
        """Zero the slot's carry rows and return it to the free list.
        Idempotent (a session abort racing a shutdown frees once)."""
        with self._cv:
            if not self._active[slot]:
                return
            self.carries = self._reset_jit(self.carries, slot)
            self._c_resets.inc()
            self._active[slot] = False
            self._free.append(slot)
            self._g_used.set(self.slots - len(self._free))
            self._cv.notify_all()

    def reset(self, slot: int) -> None:
        """Zero a slot's rows without releasing it (session restart)."""
        with self._cv:
            self.carries = self._reset_jit(self.carries, slot)
            self._c_resets.inc()

    # ------------------------------------------------------- step seam
    def swap_carries(self, new_carries) -> None:
        """Install the post-step carry tree. Callers hold `lock()` across
        the read-step-swap sequence; Condition's lock is not reentrant,
        so this method must NOT re-acquire it."""
        # graft: allow(GL301): writers hold self._cv by contract (the
        # dispatch critical section documented on lock()); re-acquiring
        # a non-reentrant Condition here would self-deadlock
        self.carries = new_carries

    # -------------------------------------------------------- hot swap
    def rebind(self, net, kv_dtype: Optional[str] = None) -> None:
        """Point the pool at a hot-swapped net, keeping the live carries
        (sessions survive the flip). The candidate must produce an
        identical carry tree — checked abstractly (eval_shape: no device
        allocation); mismatch raises IncompatibleSessionSwapError. The
        dtype comparison below covers the quantization contract too: a
        candidate whose carries come out at a different KV dtype (model
        dtype change, or `kv_dtype` explicitly different from the live
        pool's) is refused — live int8 caches cannot migrate onto a
        native-dtype tree or vice versa."""
        kd = self.kv_dtype if kv_dtype is None else kv_dtype
        want = jax.eval_shape(
            lambda: net.session_carries(self.slots, kv_dtype=kd))
        have = jax.eval_shape(lambda: self.carries)
        ws, hs = jax.tree_util.tree_structure(want), \
            jax.tree_util.tree_structure(have)
        wl = jax.tree_util.tree_leaves(want)
        hl = jax.tree_util.tree_leaves(have)
        if ws != hs or [(l.shape, l.dtype) for l in wl] != \
                [(l.shape, l.dtype) for l in hl]:
            raise IncompatibleSessionSwapError(
                f"session carries of the deploy candidate do not match "
                f"the live pool (live {hs}, candidate {ws}); live "
                f"sessions cannot migrate")
        with self._cv:
            self.net = net

    # ------------------------------------------------------ inspection
    def in_use(self) -> int:
        with self._cv:
            return self.slots - len(self._free)

    def _slot_bytes(self) -> tuple:
        """(actual, hypothetical-native) bytes per slot across the carry
        tree: KV caches counted at their stored width vs the net dtype's,
        scale rows counted vs zero. The ratio is the slots-per-chip
        multiplier quantization buys at a fixed carry budget."""
        native_itemsize = jnp.dtype(
            getattr(self.net, "dtype", jnp.float32)).itemsize
        actual = native = 0

        def walk(node):
            nonlocal actual, native
            if isinstance(node, dict):
                for kk, vv in node.items():
                    if kk in ("cache_k", "cache_v"):
                        actual += vv.size * vv.dtype.itemsize
                        native += vv.size * native_itemsize
                    elif kk in ("scale_k", "scale_v"):
                        actual += vv.size * vv.dtype.itemsize
                    else:
                        walk(vv)
            elif isinstance(node, (list, tuple)):
                for vv in node:
                    walk(vv)
            elif hasattr(node, "nbytes"):
                actual += node.nbytes
                native += node.nbytes

        walk(self.carries)
        return actual / self.slots, native / self.slots

    def describe(self) -> dict:
        with self._cv:
            actual, native = self._slot_bytes()
            return {"total": self.slots,
                    "in_use": self.slots - len(self._free),
                    "model": self.model,
                    "kv_dtype": self.kv_dtype,
                    "slot_bytes": int(actual),
                    "slots_per_chip_factor": round(
                        native / actual, 2) if actual else 1.0}
