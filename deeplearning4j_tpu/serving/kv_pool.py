"""KVSlotPool — a paged allocator over batched, slot-indexed decode
carries.

The pool owns ONE device-resident carry tree built by
`net.session_carries(slots)`: every attention layer's KV cache is
[slots, L, Hkv, Dh] with a per-slot position vector, every recurrent
layer's h/c is [slots, n]. A slot (one batch row across the whole tree)
is the unit of admission for decode sessions: `alloc()` hands a free row
to a new session, `free()` zeroes it and returns it. Nothing here ever
retraces — allocation is host bookkeeping, and the reset is a single
jitted program whose slot index is a traced scalar, so session churn
costs zero compiles (the fixed-shape decode contract the recompile
watchdog polices).

Against cross-session leakage the pool is belt-and-braces: the rolling
ring's held-position arithmetic already makes a fresh slot's stale rows
invisible (a reset position of 0 puts every old slot entry on a previous
lap, `held < 0`), AND `free()` zeroes the slot's rows anyway so a bug in
either layer cannot expose the previous session's keys/values. The
wraparound-reuse test pins both.

Occupancy rides the shared metrics spine: `serving_kv_slots` /
`serving_kv_slots_in_use` gauges plus alloc/reset counters.

Paged mode (`page_len=...`) replaces the monolithic per-slot cache with
block-granular KV pages: attention caches become [pages, L_page, Hkv,
Dh] physical pools and each slot owns a `page_table` row of physical
page indices. Pages are the unit of sharing — the prefix cache maps a
matched token prefix to a refcounted chain of read-only pages that many
sessions' tables can point at, and a session diverging inside a shared
page gets a private copy first (copy-on-write). The pool provides the
mechanism only: a page free list, per-page refcounts, and three warmed
jitted programs (`install`, `copy_page`, `poison_pages`) whose page and
slot indices are traced scalars — admission-time bookkeeping costs zero
steady-state compiles, exactly like slot alloc/reset. Policy (what to
share, when to fork, what to evict) lives in
`serving/prefix_cache.py` and `serving/sessions.py`. The `*_locked`
page methods follow the `swap_carries` contract: callers hold `lock()`
(the Condition is non-reentrant, so they must not re-acquire it).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _leaf_key(path) -> str:
    """Stable string name for a carry-tree leaf path ("layer2/cache_k"):
    the wire identity of a cache leaf in fleet KV handoff payloads."""
    parts = []
    for p in path:
        k = getattr(p, "key", None)
        parts.append(str(k) if k is not None else str(getattr(p, "idx", p)))
    return "/".join(parts)


class SlotPoolExhaustedError(RuntimeError):
    """No free KV slot (HTTP 503 — admission is slots, not queue depth)."""


class IncompatibleSessionSwapError(RuntimeError):
    """A deploy candidate's session-carry tree (shapes/dtypes/structure)
    does not match the live pool — live sessions cannot migrate onto it,
    so the deploy must roll back rather than drop them."""


class KVSlotPool:
    """Slot-indexed decode carries + free-list allocation + jitted
    per-slot reset."""

    _CACHE_KEYS = ("cache_k", "cache_v", "scale_k", "scale_v")

    def __init__(self, net, slots: int, *, model: str = "default",
                 metrics=None, kv_dtype: Optional[str] = None,
                 page_len: Optional[int] = None,
                 pages: Optional[int] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.net = net
        self.slots = int(slots)
        self.model = model
        self.kv_dtype = kv_dtype or "native"
        self.page_len = int(page_len) if page_len else None
        self._cv = threading.Condition()
        # the decode carry pytree and slot occupancy are the shared
        # state every request thread contends on; declare the guard so
        # graft-lint's interprocedural pass (GL701) checks every reader
        # — callers that enter via `with pool.lock():` stay quiet
        # graft: guarded-by(_cv)
        if self.page_len:
            self.carries = net.session_carries(
                self.slots, kv_dtype=kv_dtype, page_len=self.page_len,
                pages=pages)
        else:
            self.carries = net.session_carries(self.slots,
                                               kv_dtype=kv_dtype)
        # graft: guarded-by(_cv)
        self._free = list(range(self.slots - 1, -1, -1))
        # graft: guarded-by(_cv)
        self._active = [False] * self.slots

        # paged geometry read back off the built tree (session_carries
        # owns the defaulting): pages = physical pool size, npages =
        # page-table width (= max_cache // page_len)
        self.pages = self.npages = 0
        if self.page_len:
            for path, leaf in jax.tree_util.tree_leaves_with_path(
                    self.carries):
                key = getattr(path[-1], "key", None)
                if key == "page_table":
                    self.npages = int(leaf.shape[1])
                elif key == "cache_k":
                    self.pages = int(leaf.shape[0])
            if not (self.pages and self.npages):
                raise ValueError(
                    "page_len set but the net produced no paged "
                    "attention carries")
        # graft: guarded-by(_cv)
        self._page_free = list(range(self.pages - 1, -1, -1))
        # graft: guarded-by(_cv)
        self._page_ref = [0] * self.pages

        cache_keys = self._CACHE_KEYS

        def _reset(carries, slot):
            def z(path, a):
                # graft: allow(GL003): ndim/shape are static array
                # metadata, constant per trace — not traced values
                if getattr(a, "ndim", 0) < 1 or a.shape[0] != slots:
                    return a
                # paged mode: cache leaves are page-indexed ([pages,
                # ...]; pages may numerically equal slots) and hold
                # shared prefix pages other sessions still read —
                # reset only the slot's view (page_table / pos / h / c)
                # graft: allow(GL003): path keys are static pytree
                # metadata, constant per trace — not traced values
                if page_len and getattr(path[-1], "key", None) \
                        in cache_keys:
                    return a
                return a.at[slot].set(jnp.zeros_like(a[slot]))
            return jax.tree_util.tree_map_with_path(z, carries)

        # slot is a traced scalar: one compile covers every reset ever
        self._reset_jit = jax.jit(_reset)

        def _install(carries, slot, page_row, pos):
            def ins(path, a):
                key = getattr(path[-1], "key", None)
                # graft: allow(GL003): path keys are static metadata
                if key == "page_table":
                    return a.at[slot].set(page_row)
                # graft: allow(GL003): path keys are static metadata
                if key == "pos":
                    return a.at[slot].set(pos.astype(a.dtype))
                return a
            return jax.tree_util.tree_map_with_path(ins, carries)

        def _copy_page(carries, src, dst):
            def cp(path, a):
                # graft: allow(GL003): path keys are static metadata
                if getattr(path[-1], "key", None) in cache_keys:
                    return a.at[dst].set(a[src])
                return a
            return jax.tree_util.tree_map_with_path(cp, carries)

        def _poison(carries, page, value):
            def px(path, a):
                # graft: allow(GL003): path keys are static metadata
                if getattr(path[-1], "key", None) in cache_keys:
                    fill = jnp.full_like(a[page], value)
                    return a.at[page].set(fill)
                return a
            return jax.tree_util.tree_map_with_path(px, carries)

        # slot/page indices are traced scalars — one compile each,
        # warmed here so admission during churn never compiles
        self._install_jit = jax.jit(_install)
        self._copy_page_jit = jax.jit(_copy_page)
        self._poison_pages_jit = jax.jit(_poison)
        # compiled lazily on the first fleet KV import (page traced, so
        # one compile covers every handed-off page thereafter)
        self._import_page_jit = None
        if self.page_len:
            row = jnp.zeros((self.npages,), jnp.int32)
            self._install_jit(self.carries, 0, row, jnp.int32(0))
            self._copy_page_jit(self.carries, 0, 0)
            self._poison_pages_jit(self.carries, 0, jnp.float32(0.0))
        self._reset_jit(self.carries, 0)

        if metrics is None:
            from deeplearning4j_tpu.observe import get_registry
            metrics = get_registry()
        self._g_total = metrics.gauge("serving_kv_slots", model=model)
        self._g_used = metrics.gauge("serving_kv_slots_in_use", model=model)
        self._c_allocs = metrics.counter("serving_kv_slot_allocs_total",
                                         model=model)
        self._c_resets = metrics.counter("serving_kv_slot_resets_total",
                                         model=model)
        self._g_total.set(self.slots)
        self._g_used.set(0)
        if self.page_len:
            self._g_pages = metrics.gauge("serving_kv_pages", model=model)
            self._g_pages_free = metrics.gauge("serving_kv_pages_free",
                                               model=model)
            self._g_pages.set(self.pages)
            self._g_pages_free.set(len(self._page_free))

    def lock(self):
        """The pool lock, for the step critical section: the dispatch
        path holds it across read-carries -> session_step -> writeback so
        concurrent decode dispatches serialize on the one carry tree."""
        return self._cv

    # ------------------------------------------------------- allocation
    def alloc(self, timeout_s: float = 0.0) -> int:
        """Claim a free slot; raises SlotPoolExhaustedError when none
        frees within `timeout_s` (0 = fail fast; admission pressure maps
        to HTTP 503, not an unbounded queue)."""
        with self._cv:
            if not self._free and timeout_s > 0:
                self._cv.wait_for(lambda: bool(self._free), timeout_s)
            if not self._free:
                raise SlotPoolExhaustedError(
                    f"all {self.slots} KV slots in use")
            slot = self._free.pop()
            self._active[slot] = True
            self._c_allocs.inc()
            self._g_used.set(self.slots - len(self._free))
            return slot

    def free(self, slot: int) -> None:
        """Zero the slot's carry rows and return it to the free list.
        Idempotent (a session abort racing a shutdown frees once)."""
        with self._cv:
            if not self._active[slot]:
                return
            self.carries = self._reset_jit(self.carries, slot)
            self._c_resets.inc()
            self._active[slot] = False
            self._free.append(slot)
            self._g_used.set(self.slots - len(self._free))
            self._cv.notify_all()

    def reset(self, slot: int) -> None:
        """Zero a slot's rows without releasing it (session restart)."""
        with self._cv:
            self.carries = self._reset_jit(self.carries, slot)
            self._c_resets.inc()

    # ------------------------------------------------------ paged mode
    # All `*_locked` methods follow the swap_carries contract: the
    # caller holds `lock()` for the whole admission / teardown sequence
    # (match -> alloc -> copy -> install happens atomically w.r.t.
    # decode windows), and the Condition is non-reentrant so these must
    # not re-acquire it.

    def pages_free_locked(self) -> int:
        # graft: allow(GL301): caller holds self._cv by contract
        return len(self._page_free)

    def page_refcount_locked(self, page: int) -> int:
        # graft: allow(GL301): caller holds self._cv by contract
        # graft: allow(GL701): caller holds self._cv by contract (the
        # *_locked API — no unlocked call path exists)
        return self._page_ref[page]

    def page_alloc_locked(self, n: int) -> list:
        """Claim `n` fresh physical pages (refcount 1 each). Raises
        SlotPoolExhaustedError when the free list is short — the caller
        (prefix cache) evicts cold refcount-0 chains first and only
        then gives up."""
        # graft: allow(GL301): caller holds self._cv by contract
        if n > len(self._page_free):
            raise SlotPoolExhaustedError(
                f"need {n} KV pages, {len(self._page_free)} free "
                f"(of {self.pages})")
        # graft: allow(GL301): caller holds self._cv by contract
        out = [self._page_free.pop() for _ in range(n)]
        for p in out:
            # graft: allow(GL301): caller holds self._cv by contract
            self._page_ref[p] = 1
        self._g_pages_free.set(len(self._page_free))
        return out

    def page_ref_locked(self, page: int) -> int:
        """Take a reference on a live page (a follower session or the
        radix index adopting it)."""
        # graft: allow(GL301): caller holds self._cv by contract
        if self._page_ref[page] <= 0:
            raise ValueError(f"page {page} is not live")
        # graft: allow(GL301): caller holds self._cv by contract
        self._page_ref[page] += 1
        return self._page_ref[page]

    def page_unref_locked(self, page: int) -> int:
        """Drop a reference; a page only returns to the free list at
        refcount 0, so eviction can never reclaim a live session's
        pages. Freed pages are NOT zeroed: every offset a session can
        see is either freshly written by its own prefill/decode or part
        of a matched (still-referenced) prefix page — position
        arithmetic keeps anything else invisible, and the chaos tests
        poison freed pages to pin that."""
        # graft: allow(GL301): caller holds self._cv by contract
        if self._page_ref[page] <= 0:
            raise ValueError(f"page {page} is not live")
        # graft: allow(GL301): caller holds self._cv by contract
        self._page_ref[page] -= 1
        if self._page_ref[page] == 0:
            # graft: allow(GL301): caller holds self._cv by contract
            self._page_free.append(page)
            self._g_pages_free.set(len(self._page_free))
            self._cv.notify_all()
        return self._page_ref[page]

    def install_pages_locked(self, slot: int, pages: list,
                             pos: int) -> None:
        """Point `slot`'s page table at `pages` (padded with physical
        page 0 — a valid, DMA-able index the kernels' visibility guard
        never reads) and set its decode position. One jitted program,
        slot/row/pos traced: zero compiles at admission."""
        # graft: allow(GL301): caller holds self._cv by contract
        row = list(pages) + [0] * (self.npages - len(pages))
        # graft: allow(GL301): caller holds self._cv by contract
        self.carries = self._install_jit(
            self.carries, slot, jnp.asarray(row, jnp.int32),
            jnp.int32(pos))

    def copy_page_locked(self, src: int, dst: int) -> None:
        """Copy one physical page's K/V (+scales) — the copy-on-write
        fork at a divergence point inside a shared page."""
        # graft: allow(GL301): caller holds self._cv by contract
        self.carries = self._copy_page_jit(self.carries, src, dst)

    def poison_pages_locked(self, pages, value: float) -> None:
        """Overwrite physical pages with a sentinel (chaos tests: prove
        freed-page contents are unreachable from live sessions)."""
        v = jnp.float32(value)
        for p in pages:
            # graft: allow(GL301): caller holds self._cv by contract
            # graft: allow(GL701): caller holds self._cv by contract
            # (the *_locked API — no unlocked call path exists)
            self.carries = self._poison_pages_jit(self.carries, p, v)

    # ---------------------------------------------------- fleet handoff
    def cache_leaf_meta(self) -> dict:
        """{leaf_key: (page_shape, dtype_str)} for every per-page cache
        leaf — the schema a handoff payload must match. Static array
        metadata only; no lock needed (the tree's structure never
        changes, only its leaf values)."""
        out = {}
        with self._cv:
            carries = self.carries
        for path, leaf in jax.tree_util.tree_leaves_with_path(carries):
            if getattr(path[-1], "key", None) in self._CACHE_KEYS:
                out[_leaf_key(path)] = (tuple(leaf.shape[1:]),
                                        str(leaf.dtype))
        return out

    def export_page_locked(self, page: int) -> dict:
        """Read one physical page's K/V (+ in-page scale rows) back to
        host as {leaf_key: np.ndarray}, at the STORED dtype — int8/fp8
        pages come back as quantized bytes with their fp32 scale rows,
        never dequantized. This is a host sync; it lives on the fleet
        handoff path (admission-adjacent), never inside a decode
        window."""
        out = {}
        # graft: allow(GL301): caller holds self._cv by contract (the
        # *_locked API — serializes with decode windows so the page
        # content read is consistent)
        # graft: allow(GL701): caller holds self._cv by contract
        carries = self.carries
        for path, leaf in jax.tree_util.tree_leaves_with_path(carries):
            if getattr(path[-1], "key", None) in self._CACHE_KEYS:
                # graft: allow-sync(handoff page readback, not in decode)
                out[_leaf_key(path)] = np.asarray(leaf[page])
        return out

    def import_page_locked(self, page: int, leaves: dict) -> None:
        """Write a handed-off page's contents into physical page `page`.
        `leaves` is {leaf_key: array} exactly as `export_page_locked`
        produced it (same leaf set, shapes, dtypes — quantized bytes go
        straight into the quantized pool, no dequant round-trip). One
        jitted program with the page index traced: the first import
        compiles once, every later import (any page) reuses it."""
        meta = {}
        # graft: allow(GL301): caller holds self._cv by contract
        # graft: allow(GL701): caller holds self._cv by contract
        carries = self.carries
        for path, leaf in jax.tree_util.tree_leaves_with_path(carries):
            key = getattr(path[-1], "key", None)
            if key in self._CACHE_KEYS:
                meta[_leaf_key(path)] = (tuple(leaf.shape[1:]),
                                         str(leaf.dtype))
        if set(leaves) != set(meta):
            raise IncompatibleSessionSwapError(
                f"handoff payload leaves {sorted(leaves)} do not match "
                f"this pool's cache leaves {sorted(meta)}")
        payload = {}
        for k, arr in leaves.items():
            shape, dtype = meta[k]
            a = jnp.asarray(arr)
            if tuple(a.shape) != shape or str(a.dtype) != dtype:
                raise IncompatibleSessionSwapError(
                    f"handoff leaf {k}: got {a.shape}/{a.dtype}, pool "
                    f"holds {shape}/{dtype} — dtype-preserving install "
                    f"refused (no dequant round-trip)")
            payload[k] = a
        if getattr(self, "_import_page_jit", None) is None:
            cache_keys = self._CACHE_KEYS

            def _import(carries, page, payload):
                def wr(path, a):
                    # graft: allow(GL003): path keys are static metadata
                    if getattr(path[-1], "key", None) in cache_keys:
                        return a.at[page].set(payload[_leaf_key(path)])
                    return a
                return jax.tree_util.tree_map_with_path(wr, carries)

            # graft: allow(GL301): caller holds self._cv by contract
            self._import_page_jit = jax.jit(_import)
        # graft: allow(GL301): caller holds self._cv by contract
        # graft: allow(GL701): caller holds self._cv by contract
        self.carries = self._import_page_jit(carries, page, payload)

    # ------------------------------------------------------- step seam
    def swap_carries(self, new_carries) -> None:
        """Install the post-step carry tree. Callers hold `lock()` across
        the read-step-swap sequence; Condition's lock is not reentrant,
        so this method must NOT re-acquire it."""
        # graft: allow(GL301): writers hold self._cv by contract (the
        # dispatch critical section documented on lock()); re-acquiring
        # a non-reentrant Condition here would self-deadlock
        self.carries = new_carries

    # -------------------------------------------------------- hot swap
    def rebind(self, net, kv_dtype: Optional[str] = None) -> None:
        """Point the pool at a hot-swapped net, keeping the live carries
        (sessions survive the flip). The candidate must produce an
        identical carry tree — checked abstractly (eval_shape: no device
        allocation); mismatch raises IncompatibleSessionSwapError. The
        dtype comparison below covers the quantization contract too: a
        candidate whose carries come out at a different KV dtype (model
        dtype change, or `kv_dtype` explicitly different from the live
        pool's) is refused — live int8 caches cannot migrate onto a
        native-dtype tree or vice versa."""
        kd = self.kv_dtype if kv_dtype is None else kv_dtype
        if self.page_len:
            want = jax.eval_shape(
                lambda: net.session_carries(
                    self.slots, kv_dtype=kd, page_len=self.page_len,
                    pages=self.pages))
        else:
            want = jax.eval_shape(
                lambda: net.session_carries(self.slots, kv_dtype=kd))
        have = jax.eval_shape(lambda: self.carries)
        ws, hs = jax.tree_util.tree_structure(want), \
            jax.tree_util.tree_structure(have)
        wl = jax.tree_util.tree_leaves(want)
        hl = jax.tree_util.tree_leaves(have)
        if ws != hs or [(l.shape, l.dtype) for l in wl] != \
                [(l.shape, l.dtype) for l in hl]:
            raise IncompatibleSessionSwapError(
                f"session carries of the deploy candidate do not match "
                f"the live pool (live {hs}, candidate {ws}); live "
                f"sessions cannot migrate")
        with self._cv:
            self.net = net

    # ------------------------------------------------------ inspection
    def in_use(self) -> int:
        with self._cv:
            return self.slots - len(self._free)

    def _slot_bytes(self) -> tuple:
        """(actual, hypothetical-native) bytes per slot across the carry
        tree: KV caches counted at their stored width vs the net dtype's,
        scale rows counted vs zero. The ratio is the slots-per-chip
        multiplier quantization buys at a fixed carry budget."""
        native_itemsize = jnp.dtype(
            getattr(self.net, "dtype", jnp.float32)).itemsize
        actual = native = 0

        def walk(node):
            nonlocal actual, native
            if isinstance(node, dict):
                for kk, vv in node.items():
                    if kk in ("cache_k", "cache_v"):
                        actual += vv.size * vv.dtype.itemsize
                        native += vv.size * native_itemsize
                    elif kk in ("scale_k", "scale_v"):
                        actual += vv.size * vv.dtype.itemsize
                    else:
                        walk(vv)
            elif isinstance(node, (list, tuple)):
                for vv in node:
                    walk(vv)
            elif hasattr(node, "nbytes"):
                actual += node.nbytes
                native += node.nbytes

        walk(self.carries)
        return actual / self.slots, native / self.slots

    def describe(self) -> dict:
        with self._cv:
            actual, native = self._slot_bytes()
            out = {"total": self.slots,
                   "in_use": self.slots - len(self._free),
                   "model": self.model,
                   "kv_dtype": self.kv_dtype,
                   "slot_bytes": int(actual),
                   "slots_per_chip_factor": round(
                       native / actual, 2) if actual else 1.0}
            if self.page_len:
                out["page_len"] = self.page_len
                out["pages"] = self.pages
                out["pages_free"] = len(self._page_free)
            return out
