"""Keras-backend gateway — deeplearning4j-keras parity.

Reference parity: `deeplearning4j-keras/` (SURVEY §2.7) — a py4j
`GatewayServer` (`keras/Server.java:18`) through which Python Keras calls
`DeepLearning4jEntryPoint.fit(...)` on a .h5-exported model, plus
`HDF5MiniBatchDataSetIterator` for batch files on disk.

TPU-native redesign: py4j existed to cross the Python↔JVM boundary; here
both sides are Python, so the gateway is a plain HTTP JSON API (shared
plumbing in serving/http_base.py) any Keras user can hit from a notebook:
POST /import (h5 path) → model id, POST /fit, POST /predict, GET /models.
The h5 parsing rides keras_import (SURVEY §2.7 HDF5 ↦ native reader).
Per-model locks serialize concurrent fit/predict on one model (the request
server is threaded; a MultiLayerNetwork is not thread-safe under fit).
"""

from __future__ import annotations

import threading
from typing import Dict

import numpy as np

from deeplearning4j_tpu.serving.http_base import JsonHttpServer


class KerasGatewayServer(JsonHttpServer):
    """Serve import/fit/predict for Keras-exported models over HTTP."""

    def __init__(self, *, port: int = 0):
        super().__init__(port=port)
        self._models: Dict[str, object] = {}
        self._model_locks: Dict[str, threading.Lock] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    # -- entry-point operations (DeepLearning4jEntryPoint parity) -----
    def import_model(self, h5_path: str) -> str:
        from deeplearning4j_tpu.keras_import import (
            import_keras_model_and_weights,
        )

        net = import_keras_model_and_weights(h5_path)
        with self._lock:
            mid = f"model-{self._next_id}"
            self._next_id += 1
            self._models[mid] = net
            self._model_locks[mid] = threading.Lock()
        return mid

    def fit(self, model_id: str, x, y, *, epochs: int = 1,
            batch_size: int = 32) -> float:
        with self._lock:
            net = self._models[model_id]
            model_lock = self._model_locks[model_id]
        with model_lock:
            net.fit(np.asarray(x, np.float32), np.asarray(y, np.float32),
                    epochs=epochs, batch_size=batch_size)
            return float(net.score_)

    def predict(self, model_id: str, x):
        with self._lock:
            net = self._models[model_id]
            model_lock = self._model_locks[model_id]
        with model_lock:
            out = net.output(np.asarray(x, np.float32))
        if isinstance(out, dict):
            out = next(iter(out.values()))
        return np.asarray(out)

    # -- routes --------------------------------------------------------
    def get_routes(self):
        routes = super().get_routes()
        routes["/models"] = lambda: {"models": sorted(self._models)}
        return routes

    def post_routes(self):
        return {
            "/import": lambda req: {
                "model_id": self.import_model(req["path"])},
            "/fit": lambda req: {"score": self.fit(
                req["model_id"], req["features"], req["labels"],
                epochs=int(req.get("epochs", 1)),
                batch_size=int(req.get("batch_size", 32)))},
            "/predict": lambda req: {"output": self.predict(
                req["model_id"], req["features"]).tolist()},
        }
