"""KD-tree for low-dimensional exact NN.

Reference parity: `clustering/kdtree/KDTree.java`.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis):
        self.index = index
        self.axis = axis
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idx: List[int], depth: int) -> Optional[_KDNode]:
        if not idx:
            return None
        axis = depth % self.points.shape[1]
        idx.sort(key=lambda i: self.points[i, axis])
        mid = len(idx) // 2
        node = _KDNode(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def nn(self, target, k: int = 1) -> Tuple[List[int], List[float]]:
        target = np.asarray(target, np.float64)
        heap: List[Tuple[float, int]] = []

        def visit(node):
            if node is None:
                return
            p = self.points[node.index]
            d = float(np.linalg.norm(p - target))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = target[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff <= 0 else \
                (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        pairs = sorted([(-nd, i) for nd, i in heap])
        return [i for _, i in pairs], [d for d, _ in pairs]
