"""KMeans via jitted Lloyd iterations.

Reference parity: `clustering/kmeans/KMeansClustering.java` +
`clustering/cluster/` — k-means++ style seeding, iteration cap,
convergence by centroid movement.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _assign(points, centroids):
    # pairwise sq-distances via the matmul identity (MXU-friendly)
    p2 = jnp.sum(points * points, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d = p2 - 2.0 * points @ centroids.T + c2
    return jnp.argmin(d, axis=1)


@jax.jit
def _update(points, assign, k_onehot):
    counts = jnp.sum(k_onehot, axis=0)
    sums = k_onehot.T @ points
    return sums / jnp.maximum(counts, 1.0)[:, None], counts


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None

    def fit(self, points: np.ndarray) -> "KMeansClustering":
        pts = jnp.asarray(points, jnp.float32)
        n = pts.shape[0]
        rng = np.random.default_rng(self.seed)

        # k-means++ seeding (host; k small)
        centroids = [np.asarray(pts[rng.integers(n)])]
        for _ in range(1, self.k):
            d = np.min(
                [np.sum((np.asarray(pts) - c) ** 2, axis=1) for c in centroids],
                axis=0)
            probs = d / max(d.sum(), 1e-12)
            centroids.append(np.asarray(pts[rng.choice(n, p=probs)]))
        cent = jnp.asarray(np.stack(centroids))

        for _ in range(self.max_iterations):
            a = _assign(pts, cent)
            onehot = jax.nn.one_hot(a, self.k, dtype=jnp.float32)
            new_cent, counts = _update(pts, a, onehot)
            # keep empty clusters where they were
            new_cent = jnp.where(counts[:, None] > 0, new_cent, cent)
            move = float(jnp.max(jnp.linalg.norm(new_cent - cent, axis=1)))
            cent = new_cent
            if move < self.tol:
                break
        self.centroids = np.asarray(cent)
        return self

    def predict(self, points) -> np.ndarray:
        return np.asarray(_assign(jnp.asarray(points, jnp.float32),
                                  jnp.asarray(self.centroids)))

    def inertia(self, points) -> float:
        a = self.predict(points)
        return float(np.sum((np.asarray(points) - self.centroids[a]) ** 2))
