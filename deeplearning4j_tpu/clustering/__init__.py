"""Clustering + spatial search + t-SNE.

Reference parity: deeplearning4j-core `clustering/` (KMeans, VPTree for
k-NN, kdtree/quadtree/sptree) and `plot/BarnesHutTsne.java`.

TPU redesign: KMeans Lloyd iterations and t-SNE run as jitted dense matrix
computations (pairwise-distance matmuls on the MXU) — the reference's
Barnes-Hut tree approximations exist to avoid O(n²) on CPU; on TPU the
dense O(n²) form is faster for the dataset sizes these tools serve, so
BarnesHutTsne here is exact-t-SNE with the same API. VPTree remains a host
structure (serving-time k-NN needs low-latency single queries, not
throughput).
"""

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne

__all__ = ["KMeansClustering", "VPTree", "KDTree", "BarnesHutTsne"]
