"""Vantage-point tree for exact k-NN search.

Reference parity: `clustering/vptree/VPTree.java:39,224` — metric-space
partitioning with median-distance split; backs the nearest-neighbor server
(reference: deeplearning4j-nearestneighbor-server).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_Node"] = None
        self.outside: Optional["_Node"] = None


def _dist(a, b, metric: str):
    if metric == "euclidean":
        d = a - b
        return float(np.sqrt(np.sum(d * d)))
    if metric == "cosine":
        na = np.linalg.norm(a) + 1e-12
        nb = np.linalg.norm(b) + 1e-12
        return float(1.0 - (a @ b) / (na * nb))
    raise ValueError(metric)


class VPTree:
    def __init__(self, items: np.ndarray, metric: str = "euclidean",
                 seed: int = 0):
        self.items = np.asarray(items, np.float64)
        self.metric = metric
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.items)))
        self.root = self._build(idx)

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        i = idx[self._rng.integers(len(idx))]
        idx = [j for j in idx if j != i]
        node = _Node(i)
        if idx:
            d = np.array([_dist(self.items[i], self.items[j], self.metric)
                          for j in idx])
            med = float(np.median(d))
            node.threshold = med
            inside = [j for j, dj in zip(idx, d) if dj <= med]
            outside = [j for j, dj in zip(idx, d) if dj > med]
            node.inside = self._build(inside)
            node.outside = self._build(outside)
        return node

    def search(self, target, k: int = 1) -> Tuple[List[int], List[float]]:
        """k nearest neighbors. Reference: `VPTree.search(...):224`."""
        target = np.asarray(target, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def visit(node: Optional[_Node]):
            if node is None:
                return
            d = _dist(target, self.items[node.index], self.metric)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        pairs = sorted([(-nd, i) for nd, i in heap])
        return [i for _, i in pairs], [d for d, _ in pairs]
