"""t-SNE as jitted dense matrix iterations.

Reference parity: `plot/BarnesHutTsne.java:65` / `plot/Tsne.java:36` — the
same perplexity-calibrated P matrix, early exaggeration, and momentum
gradient descent. The reference approximates the repulsive forces with a
Barnes-Hut quadtree (CPU-friendly); on TPU the exact O(n²) pairwise form is
a couple of matmuls per iteration, so this implementation is EXACT while
keeping the reference's class name and knobs.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return s[:, None] - 2.0 * x @ x.T + s[None, :]


def _calibrate_p(dists: np.ndarray, perplexity: float, tol=1e-5, iters=50):
    """Binary-search per-point precision to hit the target perplexity
    (reference: Tsne.java computeGaussianPerplexity)."""
    n = dists.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n))
    for i in range(n):
        beta_lo, beta_hi, beta = -np.inf, np.inf, 1.0
        di = np.delete(dists[i], i)
        for _ in range(iters):
            p = np.exp(-di * beta)
            sum_p = max(p.sum(), 1e-12)
            H = np.log(sum_p) + beta * np.sum(di * p) / sum_p
            diff = H - target
            if abs(diff) < tol:
                break
            if diff > 0:
                beta_lo = beta
                beta = beta * 2 if beta_hi == np.inf else (beta + beta_hi) / 2
            else:
                beta_hi = beta
                beta = beta / 2 if beta_lo == -np.inf else (beta + beta_lo) / 2
        row = np.exp(-di * beta)
        row = row / max(row.sum(), 1e-12)
        P[i, np.arange(n) != i] = row
    return P


@partial(jax.jit, static_argnames=())
def _tsne_step(y, p, gains, velocity, momentum, lr):
    d2 = _pairwise_sq_dists(y)
    q_num = 1.0 / (1.0 + d2)
    q_num = q_num - jnp.diag(jnp.diag(q_num))
    q = q_num / jnp.maximum(jnp.sum(q_num), 1e-12)
    pq = (p - jnp.maximum(q, 1e-12)) * q_num
    grad = 4.0 * ((jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y)
    same_sign = jnp.sign(grad) == jnp.sign(velocity)
    gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    gains = jnp.maximum(gains, 0.01)
    velocity = momentum * velocity - lr * gains * grad
    y = y + velocity
    return y - jnp.mean(y, axis=0), gains, velocity


class BarnesHutTsne:
    """Reference-named exact t-SNE (see module docstring)."""

    def __init__(self, *, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 early_exaggeration: float = 12.0, momentum: float = 0.8,
                 seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.lr = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.momentum = momentum
        self.seed = seed
        self.embedding_: Optional[np.ndarray] = None

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        d2 = np.asarray(_pairwise_sq_dists(jnp.asarray(x)))
        P = _calibrate_p(d2, min(self.perplexity, (n - 1) / 3))
        P = (P + P.T) / (2 * n)
        P = np.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.standard_normal((n, self.n_components)) * 1e-2)
        gains = jnp.ones_like(y)
        vel = jnp.zeros_like(y)
        exag = int(self.n_iter * 0.25)
        p_dev = jnp.asarray(P)
        for it in range(self.n_iter):
            p_use = p_dev * self.early_exaggeration if it < exag else p_dev
            mom = 0.5 if it < exag else self.momentum
            y, gains, vel = _tsne_step(
                y, p_use, gains, vel,
                jnp.asarray(mom, jnp.float32), jnp.asarray(self.lr, jnp.float32))
        self.embedding_ = np.asarray(y)
        return self.embedding_
