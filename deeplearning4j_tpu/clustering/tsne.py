"""t-SNE as jitted dense / blocked matrix iterations.

Reference parity: `plot/BarnesHutTsne.java:65` / `plot/Tsne.java:36` — the
same perplexity-calibrated P matrix, early exaggeration, and momentum
gradient descent. The reference approximates the repulsive forces with a
Barnes-Hut quadtree over a VPTree kNN graph (CPU-friendly pointer
chasing). The TPU-native equivalents, chosen by n:

- exact (small n): the dense O(n²) pairwise form is a couple of matmuls
  per iteration — EXACT, more accurate than Barnes-Hut.
- blocked (large n): the quadtree has no TPU-shaped analogue, so scale
  comes from restructuring, not pointers: a BLOCKED kNN sweep (O(n²)
  FLOPs, O(n·b) memory) builds the same sparse symmetrized P the
  reference builds from its VPTree; attraction is a fixed-degree
  segment-sum over the 2nk sparse entries; repulsion stays EXACT but is
  computed in row blocks under `lax.map` so memory is O(n·b) instead of
  O(n²). Perplexity calibration is a vectorized binary search on device
  (the reference does a per-point scalar loop).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return s[:, None] - 2.0 * x @ x.T + s[None, :]


def _calibrate_p(dists: np.ndarray, perplexity: float, tol=1e-5, iters=50):
    """Binary-search per-point precision to hit the target perplexity
    (reference: Tsne.java computeGaussianPerplexity)."""
    n = dists.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n))
    for i in range(n):
        beta_lo, beta_hi, beta = -np.inf, np.inf, 1.0
        di = np.delete(dists[i], i)
        for _ in range(iters):
            p = np.exp(-di * beta)
            sum_p = max(p.sum(), 1e-12)
            H = np.log(sum_p) + beta * np.sum(di * p) / sum_p
            diff = H - target
            if abs(diff) < tol:
                break
            if diff > 0:
                beta_lo = beta
                beta = beta * 2 if beta_hi == np.inf else (beta + beta_hi) / 2
            else:
                beta_hi = beta
                beta = beta / 2 if beta_lo == -np.inf else (beta + beta_lo) / 2
        row = np.exp(-di * beta)
        row = row / max(row.sum(), 1e-12)
        P[i, np.arange(n) != i] = row
    return P


@partial(jax.jit, static_argnames=())
def _tsne_step(y, p, gains, velocity, momentum, lr):
    d2 = _pairwise_sq_dists(y)
    q_num = 1.0 / (1.0 + d2)
    q_num = q_num - jnp.diag(jnp.diag(q_num))
    q = q_num / jnp.maximum(jnp.sum(q_num), 1e-12)
    pq = (p - jnp.maximum(q, 1e-12)) * q_num
    grad = 4.0 * ((jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y)
    same_sign = jnp.sign(grad) == jnp.sign(velocity)
    gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    gains = jnp.maximum(gains, 0.01)
    velocity = momentum * velocity - lr * gains * grad
    y = y + velocity
    return y - jnp.mean(y, axis=0), gains, velocity


# --------------------------------------------------- blocked (large-n) path
def _pad_rows(x, block):
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,) + x.shape[1:], jnp.inf,
                                         x.dtype)])
    return x, n + pad


@partial(jax.jit, static_argnames=("k", "block"))
def _knn_blocked(x, k: int, block: int):
    """k nearest neighbors by blocked exact sweep: each `lax.map` step
    computes one [block, n] distance tile and keeps its top-k — O(n²)
    FLOPs on the MXU, O(n·block) memory (the VPTree's role in
    `BarnesHutTsne.java`, restructured for TPU)."""
    n = x.shape[0]
    xp, n_pad = _pad_rows(x, block)
    xz = jnp.where(jnp.isfinite(xp), xp, 0.0)   # hoisted out of the scan
    sq = jnp.where(jnp.isfinite(xp[:, 0]),
                   jnp.sum(xz ** 2, axis=1), jnp.inf)

    def tile(i):
        rows = jax.lax.dynamic_slice_in_dim(xz, i * block, block)
        rsq = jax.lax.dynamic_slice_in_dim(sq, i * block, block)
        d2 = rsq[:, None] - 2.0 * rows @ xz.T + sq[None, :]
        # mask self-distance and padding columns
        col = jnp.arange(n_pad)[None, :]
        row_ids = i * block + jnp.arange(block)[:, None]
        d2 = jnp.where((col == row_ids) | (col >= n), jnp.inf, d2)
        neg, idx = jax.lax.top_k(-d2, k)
        return -neg, idx

    dists, idx = jax.lax.map(tile, jnp.arange(n_pad // block))
    return (dists.reshape(n_pad, k)[:n],
            idx.reshape(n_pad, k)[:n])


@partial(jax.jit, static_argnames=("iters",))
def _calibrate_p_knn(d2, perplexity, iters: int = 50):
    """Vectorized per-point precision search over the [n, k] kNN distance
    matrix — every point's binary search advances in lockstep on device
    (reference: computeGaussianPerplexity's scalar loop)."""
    target = jnp.log(perplexity)
    n = d2.shape[0]
    # subtract the row min for numerical stability (shift-invariant H)
    d2 = d2 - d2[:, :1]

    def body(state, _):
        beta, lo, hi = state
        p = jnp.exp(-d2 * beta[:, None])
        sum_p = jnp.maximum(p.sum(1), 1e-12)
        H = jnp.log(sum_p) + beta * (d2 * p).sum(1) / sum_p
        hot = H > target            # entropy too high -> raise beta
        lo = jnp.where(hot, beta, lo)
        hi = jnp.where(hot, hi, beta)
        beta = jnp.where(
            hot,
            jnp.where(jnp.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
            jnp.where(jnp.isneginf(lo), beta / 2.0, (beta + lo) / 2.0))
        return (beta, lo, hi), None

    init = (jnp.ones(n, d2.dtype), jnp.full(n, -jnp.inf, d2.dtype),
            jnp.full(n, jnp.inf, d2.dtype))
    (beta, _, _), _ = jax.lax.scan(body, init, None, length=iters)
    p = jnp.exp(-d2 * beta[:, None])
    return p / jnp.maximum(p.sum(1, keepdims=True), 1e-12)


@partial(jax.jit, static_argnames=("block",))
def _tsne_step_blocked(y, rows, cols, vals, gains, velocity, momentum, lr,
                       block: int):
    """One gradient step with sparse attraction + blocked EXACT repulsion.

    grad_i = 4 [ Σ_j p_ij q_ij (y_i - y_j)  -  (1/Z) Σ_j q_ij² (y_i - y_j) ]
    where q_ij = 1/(1+|y_i-y_j|²). The attractive sum runs over the 2nk
    sparse symmetrized-P entries (segment_sum); the repulsive sum and Z
    are computed in [block, n] tiles so peak memory is O(n·block)."""
    n = y.shape[0]
    # attraction over sparse entries
    diff = y[rows] - y[cols]
    qn = 1.0 / (1.0 + jnp.sum(diff * diff, axis=1))
    attr = jax.ops.segment_sum((vals * qn)[:, None] * diff, rows,
                               num_segments=n)

    # blocked exact repulsion
    yp, n_pad = _pad_rows(y, block)
    yz = jnp.where(jnp.isfinite(yp), yp, 0.0)
    sq = jnp.sum(yz * yz, axis=1)

    def tile(i):
        rows_y = jax.lax.dynamic_slice_in_dim(yz, i * block, block)
        rsq = jax.lax.dynamic_slice_in_dim(sq, i * block, block)
        d2 = rsq[:, None] - 2.0 * rows_y @ yz.T + sq[None, :]
        col = jnp.arange(n_pad)[None, :]
        rid = i * block + jnp.arange(block)[:, None]
        q = 1.0 / (1.0 + d2)
        q = jnp.where((col == rid) | (col >= n) | (rid >= n), 0.0, q)
        q2 = q * q
        rep = q2.sum(1)[:, None] * rows_y - q2 @ yz
        return rep, q.sum()

    rep_blocks, z_blocks = jax.lax.map(
        tile, jnp.arange(n_pad // block))
    rep = rep_blocks.reshape(n_pad, -1)[:n]
    z = jnp.maximum(z_blocks.sum(), 1e-12)

    grad = 4.0 * (attr - rep / z)
    same_sign = jnp.sign(grad) == jnp.sign(velocity)
    gains = jnp.maximum(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
    velocity = momentum * velocity - lr * gains * grad
    y = y + velocity
    return y - jnp.mean(y, axis=0), gains, velocity


class BarnesHutTsne:
    """Reference-named t-SNE: exact dense for small n, blocked-sparse for
    large n (see module docstring). `method`: 'auto' (default — exact up
    to `exact_threshold` points), 'exact', or 'blocked'."""

    def __init__(self, *, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 early_exaggeration: float = 12.0, momentum: float = 0.8,
                 seed: int = 0, method: str = "auto",
                 exact_threshold: int = 2048, block: int = 1024,
                 n_neighbors: Optional[int] = None):
        if method not in ("auto", "exact", "blocked"):
            raise ValueError(f"method must be auto|exact|blocked, got {method!r}")
        self.n_components = n_components
        self.perplexity = perplexity
        self.lr = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.momentum = momentum
        self.seed = seed
        self.method = method
        self.exact_threshold = exact_threshold
        self.block = block
        self.n_neighbors = n_neighbors
        self.embedding_: Optional[np.ndarray] = None

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x)
        n = x.shape[0]
        method = self.method
        if method == "auto":
            method = "exact" if n <= self.exact_threshold else "blocked"
        if method == "exact":
            return self._fit_exact(np.asarray(x, np.float64))
        return self._fit_blocked(np.asarray(x, np.float32))

    def _fit_exact(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        d2 = np.asarray(_pairwise_sq_dists(jnp.asarray(x)))
        P = _calibrate_p(d2, min(self.perplexity, (n - 1) / 3))
        P = (P + P.T) / (2 * n)
        P = np.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.standard_normal((n, self.n_components)) * 1e-2)
        gains = jnp.ones_like(y)
        vel = jnp.zeros_like(y)
        exag = int(self.n_iter * 0.25)
        p_dev = jnp.asarray(P)
        for it in range(self.n_iter):
            p_use = p_dev * self.early_exaggeration if it < exag else p_dev
            mom = 0.5 if it < exag else self.momentum
            y, gains, vel = _tsne_step(
                y, p_use, gains, vel,
                jnp.asarray(mom, jnp.float32), jnp.asarray(self.lr, jnp.float32))
        self.embedding_ = np.asarray(y)
        return self.embedding_

    def _fit_blocked(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        perp = min(self.perplexity, (n - 1) / 3)
        if self.n_neighbors is not None and self.n_neighbors < 1:
            raise ValueError(f"n_neighbors must be >=1, got {self.n_neighbors}")
        k = min(n - 1, self.n_neighbors if self.n_neighbors is not None
                else max(4, int(3 * perp)))
        block = min(self.block, n)
        d2, idx = _knn_blocked(jnp.asarray(x), k, block)
        p = _calibrate_p_knn(d2.astype(jnp.float32),
                             jnp.asarray(perp, jnp.float32))

        # symmetrize the sparse P: every directed entry (i, j, p_ij/2n)
        # also contributes (j, i, p_ij/2n) — 2nk COO entries, degree-bound
        # shapes stay static for jit
        rows = jnp.repeat(jnp.arange(n), k)
        cols = idx.reshape(-1)
        vals = p.reshape(-1) / (2.0 * n)
        rows, cols = jnp.concatenate([rows, cols]), \
            jnp.concatenate([cols, rows])
        vals = jnp.concatenate([vals, vals])

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(
            rng.standard_normal((n, self.n_components)) * 1e-2, jnp.float32)
        gains = jnp.ones_like(y)
        vel = jnp.zeros_like(y)
        exag = int(self.n_iter * 0.25)
        for it in range(self.n_iter):
            v_use = vals * self.early_exaggeration if it < exag else vals
            mom = 0.5 if it < exag else self.momentum
            y, gains, vel = _tsne_step_blocked(
                y, rows, cols, v_use, gains, vel,
                jnp.asarray(mom, jnp.float32),
                jnp.asarray(self.lr, jnp.float32), block)
        self.embedding_ = np.asarray(y)
        return self.embedding_
