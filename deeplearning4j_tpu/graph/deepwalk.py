"""DeepWalk graph embeddings with degree-based Huffman hierarchical softmax.

Reference parity: `deeplearning4j-graph/.../models/deepwalk/DeepWalk.java`
(initialize from vertex degrees :67-93, fit over walk iterators :95-191,
skipgram window pairs trained via hierarchical softmax in
`models/embeddings/InMemoryGraphLookupTable.java`), Huffman coding over
degrees `models/deepwalk/GraphHuffman.java:39` (buildTree), query surface
`models/GraphVectors.java` / `models/embeddings/GraphVectorsImpl.java`
(similarity, verticesNearest), and text serialization
`models/loader/GraphVectorSerializer.java`.

TPU redesign: the reference spawns one thread per walk iterator, each doing
per-pair sigmoid updates into shared arrays (DeepWalk.java:114-156). Here the
whole walk matrix is generated vectorized (graph/walks.py) and training is
batched jitted hierarchical-softmax skipgram steps: one XLA computation
handles ~10^4 (center, context) pairs — gathers, BCE over Huffman code bits,
autodiff scatter-add, SGD.
"""

from __future__ import annotations

import heapq
import json
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.api import Graph
from deeplearning4j_tpu.graph.walks import generate_walks


class GraphHuffman:
    """Huffman coding over vertex degrees. Reference:
    `models/deepwalk/GraphHuffman.java:39` (buildTree over vertexDegree[]);
    codes cap at maxCodeLength=64 bits there, unconstrained here."""

    def __init__(self, degrees: np.ndarray):
        n = len(degrees)
        self.n_vertices = n
        self.n_inner = max(n - 1, 1)
        codes: List[List[int]] = [[] for _ in range(n)]
        points: List[List[int]] = [[] for _ in range(n)]
        if n > 1:
            heap: List[Tuple[int, int]] = [(int(degrees[i]), i)
                                           for i in range(n)]
            heapq.heapify(heap)
            parent, binary = {}, {}
            nxt = n
            while len(heap) > 1:
                c1, i1 = heapq.heappop(heap)
                c2, i2 = heapq.heappop(heap)
                parent[i1], parent[i2] = nxt, nxt
                binary[i1], binary[i2] = 0, 1
                heapq.heappush(heap, (c1 + c2, nxt))
                nxt += 1
            root = heap[0][1]
            for i in range(n):
                code, pts = [], []
                node = i
                while node != root:
                    code.append(binary[node])
                    p = parent[node]
                    pts.append(p - n)
                    node = p
                codes[i] = list(reversed(code))
                points[i] = list(reversed(pts))
        self._codes, self._points = codes, points

    def get_code(self, vertex: int) -> List[int]:
        """Reference: `GraphHuffman.getCode/getCodeString:111-131`."""
        return self._codes[vertex]

    def get_code_length(self, vertex: int) -> int:
        return len(self._codes[vertex])

    def get_path_inner_nodes(self, vertex: int) -> List[int]:
        """Reference: `GraphHuffman.getPathInnerNodes:132`."""
        return self._points[vertex]

    def padded(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        lens = np.array([len(c) for c in self._codes], dtype=np.int64)
        L = max(int(lens.max()) if len(lens) else 1, 1)
        V = self.n_vertices
        codes = np.zeros((V, L), dtype=np.int32)
        points = np.zeros((V, L), dtype=np.int32)
        for i in range(V):
            c, p = self._codes[i], self._points[i]
            codes[i, :len(c)] = c
            points[i, :len(p)] = p
        return codes, points, lens


class DeepWalk:
    """Reference: `models/deepwalk/DeepWalk.java` Builder surface
    (vectorSize :205, learningRate :211, windowSize :217, seed :226) mapped
    to kwargs; `fit(graph, walkLength)` :95."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.01, walks_per_vertex: int = 1,
                 weighted_walks: bool = False, batch_size: int = 8192,
                 epochs: int = 1, seed: int = 12345):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.walks_per_vertex = walks_per_vertex
        self.weighted_walks = weighted_walks
        self.batch_size = batch_size
        self.epochs = epochs
        self.seed = seed
        self.vertex_vectors: Optional[np.ndarray] = None  # syn0 [V,D]
        self._inner: Optional[np.ndarray] = None          # syn1 [V-1,D]
        self.huffman: Optional[GraphHuffman] = None

    # ------------------------------------------------------------ lifecycle
    def initialize(self, graph_or_degrees) -> "DeepWalk":
        """Build the Huffman tree + init vectors. Reference:
        `DeepWalk.initialize:67-93` (uniform init scaled by vector size)."""
        degrees = (graph_or_degrees.degrees()
                   if isinstance(graph_or_degrees, Graph)
                   else np.asarray(graph_or_degrees))
        # clamp isolated vertices to weight 1 so the query-facing
        # GraphHuffman and the training engine's Huffman tree are built
        # from the SAME weights (warm-start consistency)
        degrees = np.maximum(np.asarray(degrees), 1)
        V, D = len(degrees), self.vector_size
        self._degrees = degrees
        self.huffman = GraphHuffman(degrees)
        rng = np.random.default_rng(self.seed)
        self.vertex_vectors = (
            (rng.random((V, D), dtype=np.float32) - 0.5) / D)
        self._inner = np.zeros((max(V - 1, 1), D), dtype=np.float32)
        return self

    def fit(self, graph: Graph, walk_length: int = 10) -> "DeepWalk":
        """Generate walks + train. Reference: `DeepWalk.fit:95-112`."""
        if self.huffman is None:
            self.initialize(graph)
        walks = generate_walks(
            graph, walk_length=walk_length,
            walks_per_vertex=self.walks_per_vertex,
            weighted=self.weighted_walks, seed=self.seed)
        return self.fit_walks(walks)

    def fit_walks(self, walks: np.ndarray) -> "DeepWalk":
        """Train on a precomputed walk matrix [N, L] — the equivalent of
        `DeepWalk.fit(GraphWalkIterator):158-191` skipgram windows.

        Training runs on the shared SequenceVectors engine (the reference
        routes DeepWalk through SequenceVectors the same way): walks become
        element sequences, vertex DEGREES become the vocab counts (so the
        engine's count-based Huffman tree is the reference's degree-based
        GraphHuffman), full fixed window, constant learning rate."""
        from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors

        if self.huffman is None:
            raise RuntimeError("call initialize() first")
        sv = SequenceVectors(
            layer_size=self.vector_size, window=self.window_size,
            min_count=0, hierarchic_softmax=True, subsampling=0.0,
            epochs=self.epochs, learning_rate=self.learning_rate,
            min_learning_rate=self.learning_rate,   # constant LR (reference)
            batch_size=self.batch_size, seed=self.seed,
            dynamic_window=False)
        sv.initial_syn0 = self.vertex_vectors
        sv.initial_syn1 = self._inner
        # walk entries already ARE vocab indices (vertex ids) — the indexed
        # fast path skips per-element string lookups; vocab index == vertex
        # id, so trained syn0 rows come back vertex-aligned.
        sv.fit_indexed(np.asarray(walks), self._degrees)
        self.vertex_vectors = sv.syn0
        self._inner = sv._syn1
        return self

    # -------------------------------------------------------------- queries
    def get_vertex_vector(self, i: int) -> np.ndarray:
        """Reference: `GraphVectorsImpl.getVertexVector`."""
        return self.vertex_vectors[i]

    def similarity(self, a: int, b: int) -> float:
        """Cosine similarity. Reference: `GraphVectorsImpl.similarity`."""
        va, vb = self.vertex_vectors[a], self.vertex_vectors[b]
        denom = np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12
        return float(va @ vb / denom)

    def vertices_nearest(self, vertex: int, top: int = 10) -> List[int]:
        """Reference: `GraphVectorsImpl.verticesNearest`."""
        v = self.vertex_vectors[vertex]
        norms = np.linalg.norm(self.vertex_vectors, axis=1) + 1e-12
        sims = self.vertex_vectors @ v / (norms * (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        return [int(i) for i in order if i != vertex][:top]

    @property
    def num_vertices(self) -> int:
        return len(self.vertex_vectors)

    # ---------------------------------------------------------------- serde
    def save(self, path: str) -> None:
        """Text format: header json + one `index<TAB>v0 v1 ...` line per
        vertex. Reference: `GraphVectorSerializer.writeGraphVectors`."""
        with open(path, "w") as f:
            f.write(json.dumps({
                "vector_size": self.vector_size,
                "window_size": self.window_size,
                "num_vertices": self.num_vertices,
            }) + "\n")
            for i, row in enumerate(self.vertex_vectors):
                f.write(str(i) + "\t" + " ".join(
                    repr(float(x)) for x in row) + "\n")

    @classmethod
    def load(cls, path: str) -> "DeepWalk":
        """Reference: `GraphVectorSerializer.loadTxtVectors`."""
        with open(path) as f:
            head = json.loads(f.readline())
            dw = cls(vector_size=head["vector_size"],
                     window_size=head.get("window_size", 5))
            vecs = np.zeros((head["num_vertices"], head["vector_size"]),
                            dtype=np.float32)
            for line in f:
                idx, rest = line.split("\t", 1)
                vecs[int(idx)] = np.array(rest.split(), dtype=np.float32)
        dw.vertex_vectors = vecs
        return dw


class Node2Vec(DeepWalk):
    """node2vec = DeepWalk's trainer over p/q-biased second-order walks
    (Grover & Leskovec 2016). Capability extension: the reference's NLP
    stack names `models/node2vec/` but ships no complete trainer; here
    the biased `Node2VecWalker` feeds the same hierarchical-softmax
    skip-gram engine as DeepWalk."""

    def __init__(self, *, p: float = 1.0, q: float = 1.0, **kw):
        super().__init__(**kw)
        self.p = p
        self.q = q

    def fit(self, graph: Graph, walk_length: int = 10) -> "Node2Vec":
        from deeplearning4j_tpu.graph.walks import Node2VecWalker

        if self.huffman is None:
            self.initialize(graph)
        walker = Node2VecWalker(graph, walk_length, p=self.p, q=self.q,
                                seed=self.seed)
        starts = np.tile(np.arange(graph.num_vertices(), dtype=np.int64),
                         self.walks_per_vertex)
        self.fit_walks(walker.walks(starts))
        return self
