"""Graph embeddings: graph API, random walks, DeepWalk.

Reference parity: `deeplearning4j-graph/` — graph structures
(`graph/api/IGraph.java`, `graph/graph/Graph.java`), random-walk iterators
(`graph/iterator/RandomWalkIterator.java`, `WeightedRandomWalkIterator.java`),
DeepWalk (`graph/models/deepwalk/DeepWalk.java`) with degree-based Huffman
coding (`graph/models/deepwalk/GraphHuffman.java`), vector queries
(`graph/models/GraphVectors.java`) and serialization
(`graph/models/loader/GraphVectorSerializer.java`).
"""

from deeplearning4j_tpu.graph.api import (
    Edge, Graph, NoEdgeHandling, Vertex, load_edge_list,
    load_weighted_edge_list,
)
from deeplearning4j_tpu.graph.walks import (
    Node2VecWalker, RandomWalker, WeightedWalker, generate_walks,
)
from deeplearning4j_tpu.graph.deepwalk import (
    DeepWalk, GraphHuffman, Node2Vec,
)

__all__ = [
    "Edge", "Graph", "NoEdgeHandling", "Vertex", "load_edge_list",
    "load_weighted_edge_list", "Node2VecWalker", "RandomWalker",
    "WeightedWalker", "generate_walks", "DeepWalk", "GraphHuffman",
    "Node2Vec",
]
