"""Vectorized random-walk generation.

Reference parity: `deeplearning4j-graph/.../iterator/RandomWalkIterator.java`
(uniform walks), `WeightedRandomWalkIterator.java` (edge-weight-biased walks),
and the SequenceVectors graph walkers
(`deeplearning4j-nlp/.../models/sequencevectors/graph/walkers/impl/` —
RandomWalker, WeightedWalker, PopularityWalker, NearestVertexWalker).

TPU redesign: instead of one iterator object yielding one walk at a time
(the reference threads N iterators for parallelism —
`iterator/parallel/RandomWalkGraphIteratorProvider.java`), ALL walks advance
in lockstep as a single `[n_walks]` frontier vector: each step is one
vectorized gather into the padded neighbor table. Generating the full
`[n_walks, walk_length]` matrix at once feeds device-side batched skipgram
directly — no per-walk Python loop.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling


class RandomWalker:
    """Uniform random walks. Reference: `iterator/RandomWalkIterator.java`
    (next() loop choosing a uniform neighbor per step)."""

    def __init__(self, graph: Graph, walk_length: int, *, seed: int = 0,
                 no_edge_handling: NoEdgeHandling =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling

    def walks(self, starts: Optional[np.ndarray] = None) -> np.ndarray:
        """[n_walks, walk_length+1] vertex-index matrix; starts defaults to
        every vertex once (the reference iterates all vertices in order)."""
        nbrs, _, degs = self.graph.neighbor_table()
        if starts is None:
            starts = np.arange(self.graph.num_vertices(), dtype=np.int64)
        self._check_disconnected(degs, starts)
        rng = np.random.default_rng(self.seed)
        n = len(starts)
        out = np.empty((n, self.walk_length + 1), dtype=np.int64)
        out[:, 0] = starts
        cur = starts
        for t in range(1, self.walk_length + 1):
            d = degs[cur]
            choice = (rng.random(n) * np.maximum(d, 1)).astype(np.int64)
            nxt = nbrs[cur, choice]
            cur = np.where(d > 0, nxt, cur)  # self-loop on disconnected
            out[:, t] = cur
        return out

    def _check_disconnected(self, degs, starts):
        if (self.no_edge_handling is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED
                and (degs[starts] == 0).any()):
            bad = int(starts[np.argmax(degs[starts] == 0)])
            raise ValueError(
                f"Vertex {bad} has no edges "
                "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)")


class WeightedWalker(RandomWalker):
    """Edge-weight-biased walks. Reference:
    `iterator/WeightedRandomWalkIterator.java` (cumulative-weight sampling)."""

    def walks(self, starts: Optional[np.ndarray] = None) -> np.ndarray:
        nbrs, wts, degs = self.graph.neighbor_table()
        if starts is None:
            starts = np.arange(self.graph.num_vertices(), dtype=np.int64)
        self._check_disconnected(degs, starts)
        rng = np.random.default_rng(self.seed)
        # cumulative weights per row for inverse-CDF sampling
        cum = np.cumsum(wts, axis=1)
        tot = np.maximum(cum[:, -1], 1e-30)
        n = len(starts)
        out = np.empty((n, self.walk_length + 1), dtype=np.int64)
        out[:, 0] = starts
        cur = starts
        for t in range(1, self.walk_length + 1):
            u = rng.random(n) * tot[cur]
            choice = (cum[cur] < u[:, None]).sum(axis=1)
            choice = np.minimum(choice, np.maximum(degs[cur] - 1, 0))
            nxt = nbrs[cur, choice]
            cur = np.where(degs[cur] > 0, nxt, cur)
            out[:, t] = cur
        return out


class Node2VecWalker(RandomWalker):
    """node2vec p/q-biased second-order walks — capability extension beyond
    the reference (its NLP stack names `models/node2vec/` but ships no
    complete trainer); return parameter p, in-out parameter q per Grover &
    Leskovec 2016."""

    def __init__(self, graph: Graph, walk_length: int, *, p: float = 1.0,
                 q: float = 1.0, seed: int = 0,
                 no_edge_handling: NoEdgeHandling =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        super().__init__(graph, walk_length, seed=seed,
                         no_edge_handling=no_edge_handling)
        self.p = p
        self.q = q

    def walks(self, starts: Optional[np.ndarray] = None) -> np.ndarray:
        nbrs, wts, degs = self.graph.neighbor_table()
        if starts is None:
            starts = np.arange(self.graph.num_vertices(), dtype=np.int64)
        self._check_disconnected(degs, starts)
        rng = np.random.default_rng(self.seed)
        n = len(starts)
        max_d = nbrs.shape[1]
        # Sorted neighbor rows (padding → sentinel V, no vertex id collides)
        # enable a fully vectorized dist(prev, x) == 1 membership test via
        # one flat searchsorted per step — no per-row Python loops.
        V = self.graph.num_vertices()
        col = np.arange(max_d)[None, :]
        snbrs = np.sort(np.where(col < degs[:, None], nbrs, V), axis=1)
        row_off = (np.arange(n, dtype=np.int64) * (V + 2))[:, None]
        out = np.empty((n, self.walk_length + 1), dtype=np.int64)
        out[:, 0] = starts
        prev = starts.copy()
        d0 = degs[starts]
        choice = (rng.random(n) * np.maximum(d0, 1)).astype(np.int64)
        cur = np.where(d0 > 0, nbrs[starts, choice], starts)
        if self.walk_length >= 1:
            out[:, 1] = cur
        valid = np.arange(max_d)[None, :]
        for t in range(2, self.walk_length + 1):
            cand = nbrs[cur]                              # [n, max_d]
            w = wts[cur].copy()
            w[valid >= degs[cur][:, None]] = 0.0
            # bias: back to prev → w/p ; dist(prev,·)==1 → w ; else → w/q
            back = cand == prev[:, None]
            # keys are globally sorted: rows ascend, offsets jump by V+2
            sorted_keys = (snbrs[prev] + row_off).ravel()
            cand_keys = (cand + row_off).ravel()
            pos = np.searchsorted(sorted_keys, cand_keys)
            hit = pos < sorted_keys.size
            hit[hit] = sorted_keys[pos[hit]] == cand_keys[hit]
            is_nbr = hit.reshape(n, max_d)
            alpha = np.where(back, 1.0 / self.p,
                             np.where(is_nbr, 1.0, 1.0 / self.q))
            w = w * alpha
            cum = np.cumsum(w, axis=1)
            tot = np.maximum(cum[:, -1], 1e-30)
            u = rng.random(n) * tot
            choice = (cum < u[:, None]).sum(axis=1)
            choice = np.minimum(choice, np.maximum(degs[cur] - 1, 0))
            nxt = np.where(degs[cur] > 0, cand[np.arange(n), choice], cur)
            prev, cur = cur, nxt
            out[:, t] = cur
        return out


def generate_walks(graph: Graph, *, walk_length: int = 10,
                   walks_per_vertex: int = 1, weighted: bool = False,
                   seed: int = 0) -> np.ndarray:
    """All-vertices walk matrix [V * walks_per_vertex, walk_length+1] —
    the vectorized equivalent of the reference's
    `GraphWalkIteratorProvider.getGraphWalkIterators` fan-out."""
    cls = WeightedWalker if weighted else RandomWalker
    mats = []
    V = graph.num_vertices()
    for k in range(walks_per_vertex):
        walker = cls(graph, walk_length, seed=seed + k)
        mats.append(walker.walks(np.arange(V, dtype=np.int64)))
    return np.concatenate(mats, axis=0)
