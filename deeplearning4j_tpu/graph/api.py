"""Graph data structures + loaders.

Reference parity: `deeplearning4j-graph/src/main/java/org/deeplearning4j/graph/`
— `api/IGraph.java` (vertex/edge contract), `api/Vertex.java`, `api/Edge.java`,
`api/NoEdgeHandling.java`, `graph/Graph.java` (adjacency-list impl), and the
edge-list loaders `data/GraphLoader.java` +
`data/impl/{DelimitedEdgeLineProcessor,WeightedEdgeLineProcessor}.java`.

TPU redesign: vertices are dense ints and adjacency is stored as padded
numpy arrays (`[V, max_degree]` neighbor table + degree vector) so that walk
generation is fully vectorized over thousands of walkers at once — the walk
table feeds device-side batched skipgram training directly.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import numpy as np


class NoEdgeHandling(enum.Enum):
    """Reference: `graph/api/NoEdgeHandling.java` — what a walker does when
    it reaches a vertex with no outgoing edges."""

    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


@dataclasses.dataclass
class Vertex:
    """Reference: `graph/api/Vertex.java` — index + arbitrary value."""

    index: int
    value: Any = None


@dataclasses.dataclass
class Edge:
    """Reference: `graph/api/Edge.java`."""

    src: int
    dst: int
    value: Any = None
    directed: bool = False

    @property
    def weight(self) -> float:
        return float(self.value) if self.value is not None else 1.0


class Graph:
    """Adjacency-list graph over dense integer vertices.

    Reference: `graph/graph/Graph.java` (extends `api/BaseGraph.java`).
    Supports directed/undirected edges, optional weights, vertex values,
    and exports padded neighbor tables for vectorized walks.
    """

    def __init__(self, num_vertices: int, *,
                 vertex_values: Optional[Sequence[Any]] = None):
        if num_vertices <= 0:
            raise ValueError("num_vertices must be positive")
        self._adj: List[List[Tuple[int, float]]] = [
            [] for _ in range(num_vertices)]
        self.vertices = [
            Vertex(i, vertex_values[i] if vertex_values else None)
            for i in range(num_vertices)
        ]
        self._dirty = True
        self._nbr_table: Optional[np.ndarray] = None
        self._weight_table: Optional[np.ndarray] = None
        self._degrees: Optional[np.ndarray] = None

    # ------------------------------------------------------------- mutation
    def add_edge(self, src: int, dst: int, value: Any = None,
                 directed: bool = False) -> None:
        """Reference: `Graph.addEdge`. Undirected edges are stored in both
        adjacency lists (BaseGraph semantics)."""
        w = float(value) if value is not None else 1.0
        self._adj[src].append((dst, w))
        if not directed and src != dst:
            self._adj[dst].append((src, w))
        self._dirty = True

    def add_edges(self, edges: Iterable[Edge]) -> None:
        for e in edges:
            self.add_edge(e.src, e.dst, e.value, e.directed)

    # -------------------------------------------------------------- queries
    def num_vertices(self) -> int:
        return len(self._adj)

    def num_edges(self) -> int:
        return sum(len(a) for a in self._adj)

    def get_vertex(self, i: int) -> Vertex:
        return self.vertices[i]

    def get_connected_vertex_indices(self, i: int) -> List[int]:
        """Reference: `Graph.getConnectedVertexIndices`."""
        return [d for d, _ in self._adj[i]]

    def degree(self, i: int) -> int:
        """Reference: `Graph.getVertexDegree`."""
        return len(self._adj[i])

    def degrees(self) -> np.ndarray:
        self._build_tables()
        return self._degrees

    # ---------------------------------------------------- vectorized export
    def _build_tables(self) -> None:
        if not self._dirty:
            return
        V = self.num_vertices()
        degs = np.array([len(a) for a in self._adj], dtype=np.int64)
        max_d = max(int(degs.max()), 1) if V else 1
        nbrs = np.zeros((V, max_d), dtype=np.int64)
        wts = np.zeros((V, max_d), dtype=np.float64)
        for i, a in enumerate(self._adj):
            # self-loop padding keeps gather in-bounds for degree-0 rows
            nbrs[i, :] = i
            for j, (d, w) in enumerate(a):
                nbrs[i, j] = d
                wts[i, j] = w
        self._nbr_table, self._weight_table, self._degrees = nbrs, wts, degs
        self._dirty = False

    def neighbor_table(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(neighbors [V, max_deg], weights [V, max_deg], degrees [V]) —
        padded arrays for vectorized walk generation."""
        self._build_tables()
        return self._nbr_table, self._weight_table, self._degrees


def load_edge_list(path_or_lines, num_vertices: int, *, delimiter: str = ",",
                   directed: bool = False) -> Graph:
    """Unweighted edge-list loader ("src,dst" per line). Reference:
    `data/GraphLoader.loadUndirectedGraphEdgeListFile` +
    `data/impl/DelimitedEdgeLineProcessor.java`."""
    g = Graph(num_vertices)
    for line in _iter_lines(path_or_lines):
        parts = line.split(delimiter)
        if len(parts) < 2:
            continue
        g.add_edge(int(parts[0]), int(parts[1]), directed=directed)
    return g


def load_weighted_edge_list(path_or_lines, num_vertices: int, *,
                            delimiter: str = ",",
                            directed: bool = False) -> Graph:
    """Weighted edge-list loader ("src,dst,weight"). Reference:
    `data/GraphLoader.loadWeightedEdgeListFile` +
    `data/impl/WeightedEdgeLineProcessor.java`."""
    g = Graph(num_vertices)
    for line in _iter_lines(path_or_lines):
        parts = line.split(delimiter)
        if len(parts) < 3:
            continue
        g.add_edge(int(parts[0]), int(parts[1]), float(parts[2]),
                   directed=directed)
    return g


def _iter_lines(path_or_lines) -> Iterable[str]:
    if isinstance(path_or_lines, (list, tuple)):
        yield from (l.strip() for l in path_or_lines if l.strip())
        return
    with open(path_or_lines) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                yield line
