"""Reusable chart/table/text UI components with JSON serde + SVG render.

Reference parity: `deeplearning4j-ui-components/` (26 files) — the
standalone library of JSON-serializable components (ChartLine,
ChartHistogram, ChartScatter, ChartStackedArea, ChartHorizontalBar,
ChartTimeline, ComponentTable, ComponentText, ComponentDiv,
DecoratorAccordion + Style classes) that the Play UI renders client-side.

TPU-era redesign: same component-as-JSON contract (`component_type` +
config, `to_dict`/`from_dict` round-trip) but each component also renders
itself to dependency-free inline SVG/HTML server-side, so dashboards work
from a bare `http.server` with no bundled JS chart library.
"""

from __future__ import annotations

import dataclasses
import json
from html import escape
from typing import Any, Dict, List, Sequence, Tuple

COMPONENT_REGISTRY: Dict[str, type] = {}


def register_component(cls):
    COMPONENT_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass(frozen=True)
class Style:
    """Reference: ui-components `StyleChart`/`StyleText` etc. (subset)."""

    width: int = 640
    height: int = 260
    margin: int = 36
    stroke: str = "#2a6fdb"
    fill: str = "#8ab4ea"
    series_colors: Tuple[str, ...] = (
        "#2a6fdb", "#d64541", "#27ae60", "#8e44ad", "#e67e22", "#16a085")
    font_size: int = 11
    title_size: int = 14


DEFAULT_STYLE = Style()


class Component:
    """JSON contract shared by all components (reference: `Component.java`
    with the Jackson `@JsonTypeInfo` component-type tag)."""

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["component_type"] = type(self).__name__
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Component":
        d = dict(d)
        tname = d.pop("component_type")
        cls = COMPONENT_REGISTRY[tname]
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        if "children" in kw:   # container components hold sub-components
            kw["children"] = tuple(
                Component.from_dict(c) if isinstance(c, dict) else c
                for c in kw["children"])
        if "style" in kw and isinstance(kw["style"], dict):
            sf = {f.name for f in dataclasses.fields(Style)}
            sty = {k: v for k, v in kw["style"].items() if k in sf}
            if "series_colors" in sty:
                sty["series_colors"] = tuple(sty["series_colors"])
            kw["style"] = Style(**sty)
        return cls(**kw)

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))

    def render(self) -> str:
        raise NotImplementedError


# ------------------------------------------------------------------ helpers
def _axes(style: Style, xmin, xmax, ymin, ymax, title: str) -> List[str]:
    W, H, M = style.width, style.height, style.margin
    parts = [
        f'<text x="{M}" y="{style.title_size + 2}" '
        f'font-size="{style.title_size}" font-weight="bold">'
        f'{escape(title)}</text>' if title else "",
        f'<line x1="{M}" y1="{H - M}" x2="{W - M}" y2="{H - M}" '
        'stroke="#999"/>',
        f'<line x1="{M}" y1="{M}" x2="{M}" y2="{H - M}" stroke="#999"/>',
        f'<text x="{M}" y="{H - M + style.font_size + 3}" '
        f'font-size="{style.font_size}">{_fmt(xmin)}</text>',
        f'<text x="{W - M}" y="{H - M + style.font_size + 3}" '
        f'font-size="{style.font_size}" text-anchor="end">{_fmt(xmax)}</text>',
        f'<text x="{M - 3}" y="{H - M}" font-size="{style.font_size}" '
        f'text-anchor="end">{_fmt(ymin)}</text>',
        f'<text x="{M - 3}" y="{M + style.font_size}" '
        f'font-size="{style.font_size}" text-anchor="end">{_fmt(ymax)}</text>',
    ]
    return parts


def _fmt(v) -> str:
    try:
        return f"{float(v):.4g}"
    except (TypeError, ValueError):
        return str(v)


def _scales(style: Style, xmin, xmax, ymin, ymax):
    W, H, M = style.width, style.height, style.margin
    dx = (xmax - xmin) or 1.0
    dy = (ymax - ymin) or 1.0

    def sx(x):
        return M + (W - 2 * M) * (x - xmin) / dx

    def sy(y):
        return H - M - (H - 2 * M) * (y - ymin) / dy

    return sx, sy


def _svg(style: Style, inner: Sequence[str]) -> str:
    return (f'<svg xmlns="http://www.w3.org/2000/svg" '
            f'viewBox="0 0 {style.width} {style.height}" '
            f'width="{style.width}" height="{style.height}">'
            + "".join(inner) + "</svg>")


# --------------------------------------------------------------- components
@register_component
@dataclasses.dataclass(frozen=True)
class ChartLine(Component):
    """Multi-series line chart. Reference: ui-components `ChartLine.java`."""

    title: str = ""
    series_names: Tuple[str, ...] = ()
    x: Tuple[Tuple[float, ...], ...] = ()     # per-series x values
    y: Tuple[Tuple[float, ...], ...] = ()
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        xs = [v for s in self.x for v in s] or [0.0, 1.0]
        ys = [v for s in self.y for v in s] or [0.0, 1.0]
        xmin, xmax, ymin, ymax = min(xs), max(xs), min(ys), max(ys)
        sx, sy = _scales(st, xmin, xmax, ymin, ymax)
        parts = _axes(st, xmin, xmax, ymin, ymax, self.title)
        for i, (sxv, syv) in enumerate(zip(self.x, self.y)):
            color = st.series_colors[i % len(st.series_colors)]
            pts = " ".join(f"{sx(a):.1f},{sy(b):.1f}"
                           for a, b in zip(sxv, syv))
            parts.append(f'<polyline fill="none" stroke="{color}" '
                         f'stroke-width="1.5" points="{pts}"/>')
            if i < len(self.series_names):
                parts.append(
                    f'<text x="{st.width - st.margin - 4}" '
                    f'y="{st.margin + 14 * (i + 1)}" text-anchor="end" '
                    f'font-size="{st.font_size}" fill="{color}">'
                    f'{escape(self.series_names[i])}</text>')
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ChartHistogram(Component):
    """Histogram bars from bin edges + counts. Reference:
    `ChartHistogram.java` (lowerBounds/upperBounds/yValues)."""

    title: str = ""
    lower_bounds: Tuple[float, ...] = ()
    upper_bounds: Tuple[float, ...] = ()
    counts: Tuple[float, ...] = ()
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        if not self.counts:
            return _svg(st, _axes(st, 0, 1, 0, 1, self.title))
        xmin, xmax = self.lower_bounds[0], self.upper_bounds[-1]
        ymax = max(self.counts) or 1.0
        sx, sy = _scales(st, xmin, xmax, 0.0, ymax)
        parts = _axes(st, xmin, xmax, 0, ymax, self.title)
        for lo, hi, c in zip(self.lower_bounds, self.upper_bounds,
                             self.counts):
            x0, x1 = sx(lo), sx(hi)
            y0, y1 = sy(c), sy(0)
            parts.append(
                f'<rect x="{x0:.1f}" y="{y0:.1f}" '
                f'width="{max(x1 - x0 - 1, 1):.1f}" '
                f'height="{max(y1 - y0, 0):.1f}" fill="{st.fill}" '
                f'stroke="{st.stroke}" stroke-width="0.5"/>')
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ChartScatter(Component):
    """Scatter plot (t-SNE viewer backbone). Reference:
    `ChartScatter.java`."""

    title: str = ""
    series_names: Tuple[str, ...] = ()
    x: Tuple[Tuple[float, ...], ...] = ()
    y: Tuple[Tuple[float, ...], ...] = ()
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        xs = [v for s in self.x for v in s] or [0.0, 1.0]
        ys = [v for s in self.y for v in s] or [0.0, 1.0]
        xmin, xmax, ymin, ymax = min(xs), max(xs), min(ys), max(ys)
        sx, sy = _scales(st, xmin, xmax, ymin, ymax)
        parts = _axes(st, xmin, xmax, ymin, ymax, self.title)
        for i, (sxv, syv) in enumerate(zip(self.x, self.y)):
            color = st.series_colors[i % len(st.series_colors)]
            for a, b in zip(sxv, syv):
                parts.append(f'<circle cx="{sx(a):.1f}" cy="{sy(b):.1f}" '
                             f'r="2.2" fill="{color}" fill-opacity="0.7"/>')
            if i < len(self.series_names):
                parts.append(
                    f'<text x="{st.width - st.margin - 4}" '
                    f'y="{st.margin + 14 * (i + 1)}" text-anchor="end" '
                    f'font-size="{st.font_size}" fill="{color}">'
                    f'{escape(self.series_names[i])}</text>')
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ChartHorizontalBar(Component):
    """Horizontal bars (per-layer magnitudes). Reference:
    `ChartHorizontalBar.java`."""

    title: str = ""
    labels: Tuple[str, ...] = ()
    values: Tuple[float, ...] = ()
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        n = len(self.values)
        if not n:
            return _svg(st, _axes(st, 0, 1, 0, 1, self.title))
        vmax = max(max(self.values), 0) or 1.0
        H = max(st.height, 2 * st.margin + 18 * n)
        st = dataclasses.replace(st, height=H)
        bar_h = (H - 2 * st.margin) / n
        parts = [p for p in _axes(st, 0, vmax, 0, n, self.title)
                 if "<text" not in p or "bold" in p]
        for i, (lab, v) in enumerate(zip(self.labels, self.values)):
            y = st.margin + i * bar_h
            w = (st.width - 2 * st.margin) * max(v, 0) / vmax
            parts.append(
                f'<rect x="{st.margin}" y="{y:.1f}" width="{w:.1f}" '
                f'height="{bar_h - 3:.1f}" fill="{st.fill}"/>')
            parts.append(
                f'<text x="{st.margin + 3}" y="{y + bar_h / 2 + 4:.1f}" '
                f'font-size="{st.font_size}">{escape(lab)} '
                f'({_fmt(v)})</text>')
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ChartStackedArea(Component):
    """Stacked area chart. Reference: `ChartStackedArea.java`."""

    title: str = ""
    series_names: Tuple[str, ...] = ()
    x: Tuple[float, ...] = ()
    y: Tuple[Tuple[float, ...], ...] = ()     # per-series, same x
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        if not self.x or not self.y:
            return _svg(st, _axes(st, 0, 1, 0, 1, self.title))
        totals = [sum(s[i] for s in self.y) for i in range(len(self.x))]
        xmin, xmax = min(self.x), max(self.x)
        ymax = max(totals) or 1.0
        sx, sy = _scales(st, xmin, xmax, 0.0, ymax)
        parts = _axes(st, xmin, xmax, 0, ymax, self.title)
        base = [0.0] * len(self.x)
        for i, series in enumerate(self.y):
            color = st.series_colors[i % len(st.series_colors)]
            top = [b + v for b, v in zip(base, series)]
            fwd = [f"{sx(a):.1f},{sy(t):.1f}"
                   for a, t in zip(self.x, top)]
            back = [f"{sx(a):.1f},{sy(b):.1f}"
                    for a, b in zip(reversed(self.x), reversed(base))]
            parts.append(f'<polygon points="{" ".join(fwd + back)}" '
                         f'fill="{color}" fill-opacity="0.6"/>')
            base = top
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ChartTimeline(Component):
    """Lane/timeline chart (phase timing). Reference:
    `ChartTimeline.java`."""

    title: str = ""
    lanes: Tuple[str, ...] = ()
    # entries: (lane_index, start, end, label)
    entries: Tuple[Tuple[int, float, float, str], ...] = ()
    style: Style = DEFAULT_STYLE

    def render(self) -> str:
        st = self.style
        if not self.entries:
            return _svg(st, _axes(st, 0, 1, 0, 1, self.title))
        tmin = min(e[1] for e in self.entries)
        tmax = max(e[2] for e in self.entries) or tmin + 1
        n = max(len(self.lanes), 1)
        sx, _ = _scales(st, tmin, tmax, 0, 1)
        lane_h = (st.height - 2 * st.margin) / n
        parts = _axes(st, tmin, tmax, 0, n, self.title)
        for li, start, end, label in self.entries:
            y = st.margin + li * lane_h
            color = st.series_colors[li % len(st.series_colors)]
            parts.append(
                f'<rect x="{sx(start):.1f}" y="{y:.1f}" '
                f'width="{max(sx(end) - sx(start), 1):.1f}" '
                f'height="{lane_h - 4:.1f}" fill="{color}" '
                f'fill-opacity="0.7"><title>{escape(label)}</title></rect>')
        for i, lane in enumerate(self.lanes):
            parts.append(
                f'<text x="4" y="{st.margin + i * lane_h + 12:.1f}" '
                f'font-size="{st.font_size}">{escape(lane)}</text>')
        return _svg(st, parts)


@register_component
@dataclasses.dataclass(frozen=True)
class ComponentTable(Component):
    """Header + rows. Reference: `ComponentTable.java`."""

    title: str = ""
    header: Tuple[str, ...] = ()
    rows: Tuple[Tuple[str, ...], ...] = ()

    def render(self) -> str:
        head = "".join(f"<th>{escape(str(h))}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{escape(str(c))}</td>" for c in row)
            + "</tr>" for row in self.rows)
        cap = (f"<caption style='font-weight:bold;text-align:left'>"
               f"{escape(self.title)}</caption>" if self.title else "")
        return (f"<table class='uic'>{cap}<tr>{head}</tr>{body}</table>")


@register_component
@dataclasses.dataclass(frozen=True)
class ComponentText(Component):
    """Reference: `ComponentText.java`."""

    text: str = ""

    def render(self) -> str:
        return f"<p class='uic'>{escape(self.text)}</p>"


@register_component
@dataclasses.dataclass(frozen=True)
class ComponentDiv(Component):
    """Container of child components. Reference: `ComponentDiv.java`."""

    children: Tuple[Any, ...] = ()

    def to_dict(self) -> Dict[str, Any]:
        return {"component_type": "ComponentDiv",
                "children": tuple(
                    c.to_dict() if isinstance(c, Component) else c
                    for c in self.children)}

    def render(self) -> str:
        inner = "".join(
            (c if isinstance(c, Component) else Component.from_dict(c))
            .render() for c in self.children)
        return f"<div class='uic'>{inner}</div>"


@register_component
@dataclasses.dataclass(frozen=True)
class DecoratorAccordion(Component):
    """Collapsible section. Reference: `DecoratorAccordion.java`."""

    title: str = ""
    children: Tuple[Any, ...] = ()
    default_collapsed: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {"component_type": "DecoratorAccordion",
                "title": self.title,
                "default_collapsed": self.default_collapsed,
                "children": tuple(
                    c.to_dict() if isinstance(c, Component) else c
                    for c in self.children)}

    def render(self) -> str:
        inner = "".join(
            (c if isinstance(c, Component) else Component.from_dict(c))
            .render() for c in self.children)
        open_attr = "" if self.default_collapsed else " open"
        return (f"<details class='uic'{open_attr}>"
                f"<summary>{escape(self.title)}</summary>{inner}</details>")


def histogram_component(name: str, hist: Dict[str, Any],
                        style: Style = DEFAULT_STYLE) -> ChartHistogram:
    """Adapter: StatsListener histogram record → ChartHistogram."""
    counts = hist.get("counts", [])
    lo, hi = hist.get("min", 0.0), hist.get("max", 1.0)
    n = len(counts) or 1
    w = (hi - lo) / n
    return ChartHistogram(
        title=name,
        lower_bounds=tuple(lo + i * w for i in range(n)),
        upper_bounds=tuple(lo + (i + 1) * w for i in range(n)),
        counts=tuple(float(c) for c in counts),
        style=style)
