"""Convolutional-activations UI module: feature-map rendering.

Reference parity: `ui/module/convolutional/ConvolutionalListenerModule.java:29-52`
(+ `ui/weights/ConvolutionalIterationListener.java`): a listener renders
the conv layers' activations for the current minibatch into one tiled
grayscale image, posts it as static info typed "ConvolutionalListener",
and the UI serves the latest image at /activations (+ /activations/data).

TPU-native differences: the listener runs one extra jitted forward on a
slice of the last training batch (activations are not host-visible
mid-step — the step is one fused XLA program), and the image is a PNG
written by a dependency-free encoder (stdlib zlib; the reference uses
BufferedImage/ImageIO jpg).
"""

from __future__ import annotations

import base64
import struct
import zlib
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.optim.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import Persistable

TYPE_ID = "ConvolutionalListener"

# 1x1 transparent-ish placeholder served before any report lands
# (reference returns empty bytes; an actual tiny PNG renders cleanly)
_EMPTY: Optional[bytes] = None


def encode_grayscale_png(img: np.ndarray) -> bytes:
    """Minimal 8-bit grayscale PNG encoder (pure stdlib). `img` is
    [H, W] uint8."""
    img = np.asarray(img, np.uint8)
    h, w = img.shape

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # 8-bit grayscale
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6)) + chunk(b"IEND", b""))


def empty_png() -> bytes:
    global _EMPTY
    if _EMPTY is None:
        _EMPTY = encode_grayscale_png(np.zeros((1, 1), np.uint8))
    return _EMPTY


def tile_feature_maps(act: np.ndarray, *, max_maps: int = 64,
                      pad: int = 1, example: int = 0) -> np.ndarray:
    """Tile one example's [H, W, C] feature maps into a near-square
    [rows*H', cols*W'] uint8 grid, each map min-max normalized (the
    reference normalizes per-map before drawing into the grid)."""
    if act.ndim == 4:
        act = act[example]
    h, w, c = act.shape
    c = min(c, max_maps)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    out = np.zeros((rows * (h + pad) + pad, cols * (w + pad) + pad),
                   np.uint8)
    for i in range(c):
        m = np.asarray(act[:, :, i], np.float32)
        lo, hi = float(m.min()), float(m.max())
        scaled = ((m - lo) / (hi - lo) * 255.0 if hi > lo
                  else np.zeros_like(m)).astype(np.uint8)
        r, col = divmod(i, cols)
        y0 = pad + r * (h + pad)
        x0 = pad + col * (w + pad)
        out[y0:y0 + h, x0:x0 + w] = scaled
    return out


def render_activation_grid(acts: List[np.ndarray], *,
                           max_maps: int = 64,
                           examples: int = 1) -> bytes:
    """Stack each conv layer's tiled grid vertically into one PNG (the
    reference's single combined BufferedImage); with examples > 1 each
    layer contributes one tiled grid per rendered example."""
    tiles = [tile_feature_maps(np.asarray(a), max_maps=max_maps,
                               example=e)
             for a in acts
             for e in range(min(examples, np.asarray(a).shape[0])
                            if np.asarray(a).ndim == 4 else 1)]
    if not tiles:
        return empty_png()
    width = max(t.shape[1] for t in tiles)
    sep = 3
    rows = []
    for t in tiles:
        if t.shape[1] < width:
            t = np.pad(t, ((0, 0), (0, width - t.shape[1])))
        rows.append(t)
        rows.append(np.full((sep, width), 32, np.uint8))  # separator band
    return encode_grayscale_png(np.concatenate(rows[:-1]))


class ConvolutionalIterationListener(TrainingListener):
    """Reference: `ui/weights/ConvolutionalIterationListener.java` — every
    `frequency` iterations, render the conv-layer activations of (a slice
    of) the current minibatch and post them as a static-info Persistable
    the ConvolutionalListenerModule serves."""

    def __init__(self, router, frequency: int = 10, *,
                 session_id: Optional[str] = None, worker_id: str = "local",
                 max_maps: int = 64, examples: int = 1):
        import uuid

        self.router = router
        self.frequency = max(frequency, 1)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        self.max_maps = max_maps
        self.examples = examples
        self._count = 0

    def iteration_done(self, model, iteration, epoch, score):
        self._count += 1
        if self._count % self.frequency:
            return
        feats = getattr(model, "_last_features", None)
        ff = getattr(model, "feed_forward", None)
        if feats is None or ff is None:
            return
        sample = np.asarray(feats)[:self.examples]
        acts = ff(sample)
        layers = getattr(model.conf, "layers", [])
        conv_acts, names = [], []
        for layer, a in zip(layers, acts):
            a = np.asarray(a)
            if a.ndim == 4:  # NHWC feature maps
                conv_acts.append(a)
                names.append(layer.name)
        if not conv_acts:
            return
        import time

        png = render_activation_grid(conv_acts, max_maps=self.max_maps,
                                     examples=self.examples)
        self.router.put_static_info(Persistable(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(),
            content={
                "iteration": int(iteration),
                "layers": names,
                "png_b64": base64.b64encode(png).decode("ascii"),
            }))


def latest_activation_png(storage) -> bytes:
    """The newest ConvolutionalListener static record's PNG across all
    sessions (reference getImage(): latest PostStaticInfo event wins;
    empty image when none)."""
    best = None
    for sid in storage.list_session_ids():
        for wid in storage.list_worker_ids(sid, TYPE_ID):
            p = storage.get_static_info(sid, TYPE_ID, wid)
            if p is not None and (best is None
                                  or p.timestamp > best.timestamp):
                best = p
    if best is None or "png_b64" not in best.content:
        return empty_png()
    return base64.b64decode(best.content["png_b64"])
