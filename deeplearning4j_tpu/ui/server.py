"""Training UI server + remote stats routing.

Reference parity: `deeplearning4j-play/.../ui/play/PlayUIServer.java` —
`getInstance()` singleton, `attach(statsStorage):254`, port via the
`org.deeplearning4j.ui.port` system property (:59), remote-listener endpoint
`enableRemoteListener():313`; dashboards served by `ui/module/train/
TrainModule.java` (overview score chart, model param charts, system tab).
Remote side: `deeplearning4j-core/.../impl/RemoteUIStatsStorageRouter.java:33`
(HTTP POST of records, retry queue) + `ui/module/remote/
RemoteReceiverModule.java` (receiving endpoint).

TPU redesign: a dependency-free `http.server` dashboard (the reference
embeds a Play framework app); charts are inline SVG polled via JSON
endpoints. The server is read-only over the `StatsStorage` API, exactly
like the reference's UIModule seam.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.ui.storage import (
    Persistable, StatsStorage, StatsStorageRouter,
)

_PAGE = """<!doctype html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:24px;background:#fafafa}
 h1{font-size:20px} h2{font-size:16px}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:12px;margin-bottom:16px;max-width:900px}
 svg{width:100%;height:220px} .meta{color:#666;font-size:13px}
 polyline{fill:none;stroke:#2a6fdb;stroke-width:1.5}
 table{border-collapse:collapse;font-size:13px}
 td,th{border:1px solid #ddd;padding:4px 8px;text-align:right}
 th:first-child,td:first-child{text-align:left}
</style></head><body>
<h1>Training overview</h1>
<div class=card><h2>Score vs iteration</h2><svg id=score></svg>
<div class=meta id=perf></div></div>
<div class=card><h2>Parameter norms (last report)</h2>
<table id=params><tr><th>parameter</th><th>norm2</th><th>mean mag</th>
<th>update norm2</th></tr></table></div>
<div class=card><h2>Session</h2><div class=meta id=session></div></div>
<script>
function line(svg, xs, ys){
  if(!ys.length){return}
  const W=880,H=220,P=30;
  const xmax=Math.max(...xs,1), ymin=Math.min(...ys), ymax=Math.max(...ys);
  const sx=x=>P+(W-2*P)*x/xmax, sy=y=>H-P-(H-2*P)*(y-ymin)/((ymax-ymin)||1);
  svg.setAttribute('viewBox',`0 0 ${W} ${H}`);
  svg.innerHTML=`<text x=4 y=14 font-size=11>${ymax.toPrecision(4)}</text>`+
    `<text x=4 y=${H-8} font-size=11>${ymin.toPrecision(4)}</text>`+
    `<polyline points="${xs.map((x,i)=>sx(x)+','+sy(ys[i])).join(' ')}"/>`;
}
async function tick(){
  try{
    const r=await (await fetch('train/overview')).json();
    line(document.getElementById('score'), r.iterations, r.scores);
    document.getElementById('perf').textContent =
      `${r.scores.length} reports; last score ${r.scores.at(-1)?.toPrecision(6)??'-'}; `+
      `${(r.minibatches_per_second??0).toFixed(2)} minibatches/s; `+
      `rss ${(r.memory_rss_mb??0).toFixed(0)} MB`;
    const t=document.getElementById('params');
    t.innerHTML='<tr><th>parameter</th><th>norm2</th><th>mean mag</th><th>update norm2</th></tr>';
    for(const [k,v] of Object.entries(r.param_stats||{})){
      const u=(r.update_stats||{})[k]||{};
      t.innerHTML+=`<tr><td>${k}</td><td>${v.norm2?.toPrecision(5)}</td>`+
        `<td>${v.mean_magnitude?.toPrecision(5)}</td>`+
        `<td>${u.norm2?.toPrecision(5)??'-'}</td></tr>`;
    }
    document.getElementById('session').textContent=JSON.stringify(r.static||{});
  }catch(e){}
  setTimeout(tick, 2000);
}
tick();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"

    def log_message(self, *a):  # silence request logging
        pass

    # --------------------------------------------------------------- GET
    def do_GET(self):
        storage: Optional[StatsStorage] = self.server.ui.storage
        path = self.path.split("?")[0].rstrip("/")
        if path in ("", "/", "/train", "/train/overview.html"):
            return self._send(200, _PAGE, "text/html")
        if path == "/train/overview":
            return self._send_json(self._overview(storage))
        if path == "/train/sessions":
            sids = storage.list_session_ids() if storage else []
            return self._send_json({"sessions": sids})
        self._send(404, "not found", "text/plain")

    def _overview(self, storage):
        if storage is None:
            return {"iterations": [], "scores": []}
        out = {"iterations": [], "scores": []}
        sids = storage.list_session_ids()
        if not sids:
            return out
        sid = sids[-1]
        for tid in storage.list_type_ids(sid):
            for wid in storage.list_worker_ids(sid, tid):
                ups = storage.get_all_updates(sid, tid, wid)
                for u in ups:
                    if "score" in u.content:
                        out["iterations"].append(u.content.get("iteration"))
                        out["scores"].append(u.content["score"])
                if ups:
                    last = ups[-1].content
                    out["param_stats"] = last.get("param_stats")
                    out["update_stats"] = last.get("update_stats")
                    out["minibatches_per_second"] = last.get(
                        "minibatches_per_second")
                    out["memory_rss_mb"] = last.get("memory_rss_mb")
                st = storage.get_static_info(sid, tid, wid)
                if st:
                    out["static"] = {
                        "model_class": st.content.get("model_class"),
                        "num_params": st.content.get("num_params"),
                        "backend": (st.content.get("software") or {}).get(
                            "backend"),
                    }
        return out

    # --------------------------------------------------------------- POST
    def do_POST(self):
        """Remote-listener receiver. Reference:
        `RemoteReceiverModule.java` paired with PlayUIServer
        `enableRemoteListener():313`."""
        ui = self.server.ui
        if self.path.rstrip("/") != "/remote" or not ui.remote_enabled:
            return self._send(404, "remote receiver not enabled",
                              "text/plain")
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        rec = Persistable(**body["record"])
        if ui.storage is not None:
            if body.get("kind") == "static":
                ui.storage.put_static_info(rec)
            else:
                ui.storage.put_update(rec)
        self._send_json({"ok": True})

    # ------------------------------------------------------------ helpers
    def _send(self, code, body, ctype):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, obj):
        self._send(200, json.dumps(obj), "application/json")


class UIServer:
    """Reference: `PlayUIServer` — `getInstance()`, `attach(storage)`,
    `enableRemoteListener()`. Port 0 picks a free port (the reference
    defaults to 9000 via the ui.port property)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0):
        self.storage: Optional[StatsStorage] = None
        self.remote_enabled = False
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.ui = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def attach(self, storage: StatsStorage) -> None:
        self.storage = storage

    def detach(self, storage: StatsStorage) -> None:
        if self.storage is storage:
            self.storage = None

    def enable_remote_listener(self) -> None:
        self.remote_enabled = True
        if self.storage is None:
            self.storage = StatsStorage()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteStatsRouter(StatsStorageRouter):
    """HTTP-POST router to a remote UIServer. Reference:
    `impl/RemoteUIStatsStorageRouter.java:33` (posts records, silently
    retries/drops on failure so training never blocks on the UI)."""

    def __init__(self, url: str, *, timeout: float = 2.0,
                 raise_on_error: bool = False):
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self.raise_on_error = raise_on_error

    def _post(self, kind: str, record: Persistable) -> None:
        import dataclasses as dc
        body = json.dumps({"kind": kind,
                           "record": dc.asdict(record)}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=self.timeout).read()
        except Exception:
            if self.raise_on_error:
                raise

    def put_static_info(self, record: Persistable) -> None:
        self._post("static", record)

    def put_update(self, record: Persistable) -> None:
        self._post("update", record)
