"""Training UI server: overview / model / system dashboards + t-SNE viewer
+ remote stats routing.

Reference parity: `deeplearning4j-play/.../ui/play/PlayUIServer.java` —
`getInstance()` singleton, `attach(statsStorage):254`, remote-listener
endpoint `enableRemoteListener():313`; dashboards served by UIModules:
`ui/module/train/TrainModule.java` (overview score chart, per-layer
param/update charts + histograms + activation charts, system tab) and
`ui/module/tsne/` (t-SNE embedding viewer). Remote side:
`impl/RemoteUIStatsStorageRouter.java:33` (HTTP POST of records) +
`ui/module/remote/RemoteReceiverModule.java`.

TPU redesign: a dependency-free `http.server` app (the reference embeds a
Play framework app with Scala templates); every chart on every page is a
`ui/components.py` component rendered server-side to inline SVG — the same
reusable JSON components are also served raw under `/train/*` endpoints
for programmatic consumers. The server is read-only over the
`StatsStorage` API, exactly like the reference's UIModule seam.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.ui.client_js import APP_JS
from deeplearning4j_tpu.ui.components import (
    ChartLine, ChartScatter, ComponentDiv, ComponentTable,
    DecoratorAccordion, Style, histogram_component,
)
from deeplearning4j_tpu.ui.storage import (
    Persistable, StatsStorage, StatsStorageRouter,
)

TSNE_TYPE_ID = "Tsne"

_CSS = """
 body{font-family:sans-serif;margin:24px;background:#fafafa}
 h1{font-size:20px} nav a{margin-right:14px;font-size:14px}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:12px;margin-bottom:16px;max-width:980px}
 .meta{color:#666;font-size:13px}
 table.uic{border-collapse:collapse;font-size:13px;margin:8px 0}
 table.uic td,table.uic th{border:1px solid #ddd;padding:4px 8px;
       text-align:right}
 table.uic th:first-child,table.uic td:first-child{text-align:left}
 details.uic{margin:6px 0} details.uic>summary{cursor:pointer;
       font-weight:bold;font-size:14px}
"""


def _page(title: str, body_html: str, page: str = "") -> str:
    """Page shell: server-rendered SVG snapshot inside #live (no-JS
    fallback, refreshed by <noscript> meta), overwritten every 2 s by the
    polling client /js/app.js (reference: the Play UI's flot-based JS
    polling dashboards). Nav chrome is localized through the i18n layer
    (reference: DefaultI18N + train.nav.* resource keys)."""
    from deeplearning4j_tpu.ui.i18n import i18n

    t = i18n().get_message
    langs = "".join(
        f'<a href="/setlang/{code}">{code}</a>'
        for code in i18n().languages())
    nav = (f'<nav><a href="/train/overview.html">'
           f'{t("train.nav.overview")}</a>'
           f'<a href="/train/model.html">{t("train.nav.model")}</a>'
           f'<a href="/train/histogram.html">'
           f'{t("train.nav.histogram")}</a>'
           f'<a href="/train/flow.html">{t("train.nav.flow")}</a>'
           f'<a href="/train/system.html">{t("train.nav.system")}</a>'
           f'<a href="/tsne.html">{t("train.nav.tsne")}</a>'
           f'<a href="/train/activations.html">'
           f'{t("train.nav.activations")}</a>'
           f'<span class=meta> {t("train.nav.language")}: {langs}'
           '</span></nav>')
    return (f"<!doctype html><html><head><meta charset=utf-8>"
            f"<title>{title}</title>"
            f"<style>{_CSS}</style>"
            "<noscript><meta http-equiv=refresh content=5></noscript>"
            f"</head><body data-page=\"{page}\"><h1>{title}</h1>{nav}"
            '<div id=status class=meta></div>'
            f"<div id=live>{body_html}</div>"
            '<script src="/js/app.js"></script></body></html>')


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/2.0"

    def log_message(self, *a):  # silence request logging
        pass

    # --------------------------------------------------------------- GET
    def do_GET(self):
        storage: Optional[StatsStorage] = self.server.ui.storage
        path, _, query = self.path.partition("?")
        path = path.rstrip("/")
        routes = {
            "": lambda: self._send(200, _page(
                "Training overview", self._overview_html(storage),
                "overview"), "text/html"),
            "/train": None, "/train/overview.html": None,
            "/train/overview": lambda: self._send_json(
                self._overview(storage)),
            "/train/model": lambda: self._send_json(
                self._model_data(storage)),
            "/train/model.html": lambda: self._send(200, _page(
                "Model", self._model_html(storage), "model"), "text/html"),
            "/train/model/components": lambda: self._send_json(
                self._model_components(storage).to_dict()),
            "/train/histogram": lambda: self._send_json(
                self._histogram_data(storage)),
            "/train/histogram.html": lambda: self._send(200, _page(
                "Histograms", self._histogram_html(storage), "histogram"),
                "text/html"),
            "/train/flow": lambda: self._send_json(
                self._flow_data(storage)),
            "/train/flow.html": lambda: self._send(200, _page(
                "Network flow", self._flow_html(storage), "flow"),
                "text/html"),
            "/train/updates": lambda: self._send_json(
                self._updates_since(storage, query)),
            "/train/system": lambda: self._send_json(
                self._system_data(storage)),
            "/train/system.html": lambda: self._send(200, _page(
                "System", self._system_html(storage), "system"),
                "text/html"),
            "/train/sessions": lambda: self._send_json(
                {"sessions":
                 storage.list_session_ids() if storage else []}),
            "/tsne": lambda: self._send_json(self._tsne_data(storage)),
            "/tsne.html": lambda: self._send(200, _page(
                "t-SNE", self._tsne_html(storage), "tsne"), "text/html"),
            "/js/app.js": lambda: self._send(
                200, APP_JS, "text/javascript"),
            # reference: ConvolutionalListenerModule routes /activations
            # (page) + /activations/data (latest rendered image)
            "/train/activations.html": lambda: self._send(
                200, self._activations_html(), "text/html"),
            "/train/activations": lambda: self._send(
                200, self._activations_html(), "text/html"),
            "/train/activations/data": lambda: self._send(
                200, self._activations_png(storage), "image/png"),
            "/lang": lambda: self._send_json(self._lang_data()),
        }
        if path.startswith("/setlang/"):
            return self._set_lang(path.rsplit("/", 1)[1])
        fn = routes.get(path, routes[""] if path == "/" else None)
        if fn is None and path in routes:   # aliases to overview page
            fn = routes[""]
        if fn is None:
            return self._send(404, "not found", "text/plain")
        return fn()

    # ------------------------------------------- conv activations + i18n
    def _activations_png(self, storage) -> bytes:
        from deeplearning4j_tpu.ui.convolutional import (
            empty_png, latest_activation_png,
        )

        if storage is None:
            return empty_png()
        return latest_activation_png(storage)

    def _activations_html(self) -> str:
        from deeplearning4j_tpu.ui.i18n import i18n

        title = i18n().get_message("train.activations.title")
        body = ('<img id=actimg src="/train/activations/data" '
                'alt="conv activations" '
                'style="image-rendering:pixelated;min-width:256px">'
                "<script>setInterval(function(){"
                "document.getElementById('actimg').src="
                "'/train/activations/data?t='+Date.now();},2000);"
                "</script>")
        return _page(title, body, "activations")

    def _lang_data(self):
        from deeplearning4j_tpu.ui.i18n import i18n

        return {"current": i18n().get_default_language(),
                "available": i18n().languages()}

    def _set_lang(self, code: str):
        from deeplearning4j_tpu.ui.i18n import i18n

        if code in i18n().languages():
            i18n().set_default_language(code)
        self.send_response(302)
        self.send_header("Location", "/train/overview.html")
        self.end_headers()

    # ----------------------------------------------------- data assembly
    def _updates(self, storage) -> List[Persistable]:
        """All StatsListener updates of the latest session, time-ordered
        (multi-worker records interleave, like the reference's train
        module merging worker streams)."""
        if storage is None:
            return []
        sids = [s for s in storage.list_session_ids()]
        stats_sids = [
            s for s in sids if "StatsListener" in storage.list_type_ids(s)]
        if not stats_sids:
            return []
        sid = stats_sids[-1]
        ups: List[Persistable] = []
        for wid in storage.list_worker_ids(sid, "StatsListener"):
            ups.extend(storage.get_all_updates(sid, "StatsListener", wid))
        ups.sort(key=lambda u: u.timestamp)
        return ups

    def _static(self, storage) -> Dict[str, Any]:
        if storage is None:
            return {}
        # image-typed records (ConvolutionalListener) also live in static
        # storage; the dashboards' metadata must come from a model-info
        # record, so prefer StatsListener and fall back to anything else
        for only_stats in (True, False):
            for sid in reversed(storage.list_session_ids()):
                for tid in storage.list_type_ids(sid):
                    if only_stats != (tid == "StatsListener"):
                        continue
                    for wid in storage.list_worker_ids(sid, tid):
                        st = storage.get_static_info(sid, tid, wid)
                        if st:
                            return st.content
        return {}

    def _overview(self, storage):
        ups = self._updates(storage)
        out: Dict[str, Any] = {"iterations": [], "scores": []}
        for u in ups:
            if "score" in u.content:
                out["iterations"].append(u.content.get("iteration"))
                out["scores"].append(u.content["score"])
        if ups:
            last = ups[-1].content
            out["param_stats"] = last.get("param_stats")
            out["update_stats"] = last.get("update_stats")
            out["minibatches_per_second"] = last.get(
                "minibatches_per_second")
            out["memory_rss_mb"] = last.get("memory_rss_mb")
        st = self._static(storage)
        if st:
            out["static"] = {
                "model_class": st.get("model_class"),
                "num_params": st.get("num_params"),
                "backend": (st.get("software") or {}).get("backend"),
            }
        return out

    def _model_data(self, storage):
        """Per-layer norm timelines + ratio + histograms + activations —
        the TrainModule 'model' tab payload."""
        ups = self._updates(storage)
        layers: Dict[str, Dict[str, list]] = {}
        activations: Dict[str, Dict[str, list]] = {}
        histograms: Dict[str, Any] = {}
        update_hist: Dict[str, Any] = {}
        for u in ups:
            c = u.content
            it = c.get("iteration")
            for name, st in (c.get("param_stats") or {}).items():
                d = layers.setdefault(name, {
                    "iterations": [], "param_norm": [], "mean_magnitude": [],
                    "update_norm": [], "ratio": []})
                d["iterations"].append(it)
                d["param_norm"].append(st.get("norm2"))
                d["mean_magnitude"].append(st.get("mean_magnitude"))
                ust = (c.get("update_stats") or {}).get(name) or {}
                un = ust.get("norm2")
                d["update_norm"].append(un)
                pn = st.get("norm2") or 0.0
                d["ratio"].append(
                    (un / pn) if (un is not None and pn > 0) else None)
            for name, st in (c.get("activation_stats") or {}).items():
                d = activations.setdefault(name, {
                    "iterations": [], "mean": [], "std": [],
                    "mean_magnitude": []})
                d["iterations"].append(it)
                for k in ("mean", "std", "mean_magnitude"):
                    d[k].append(st.get(k))
            if c.get("param_histograms"):
                histograms = c["param_histograms"]   # keep latest
            if c.get("update_histograms"):
                update_hist = c["update_histograms"]
        return {"layers": layers, "activations": activations,
                "param_histograms": histograms,
                "update_histograms": update_hist}

    def _updates_since(self, storage, query: str):
        """Incremental polling endpoint: records newer than ?since=<ts>
        (epoch seconds). The delta contract for programmatic clients —
        the page client re-reads aggregates instead, but this endpoint
        lets a tool tail a run without re-downloading history."""
        since = 0.0
        for part in query.split("&"):
            if part.startswith("since="):
                try:
                    since = float(part[6:])
                except ValueError:  # graft: allow(GL403): malformed
                    pass            # since= falls back to full history
        # one collection path for first and incremental polls, so the
        # session scope never shifts between them (the latest session,
        # via _updates) — a per-timestamp storage index can slot in here
        # if linear rescans ever show up in profiles
        ups = [u for u in self._updates(storage) if u.timestamp > since]
        # At-least-once contract: the cursor trails the max delivered
        # record timestamp by a grace window, because listeners stamp
        # BEFORE storing (tens of ms of histogram building) and multiple
        # workers' stamps interleave — a strict max cursor would skip a
        # record stamped before the poll but stored after it. Clients
        # dedup by (worker_id, timestamp); records inside the window are
        # re-delivered, never lost.
        grace = 1.0
        now = max((u.timestamp for u in ups), default=since + grace) - grace
        now = max(now, since)    # cursor never moves backwards
        return {"now": now,
                "records": [{"timestamp": u.timestamp,
                             "worker_id": u.worker_id,
                             "content": u.content} for u in ups]}

    def _histogram_data(self, storage):
        """Latest param/update histograms — the HistogramModule payload
        (reference: `ui/module/histogram/HistogramModule.java`)."""
        ups = self._updates(storage)
        out = {"iteration": None, "param_histograms": {},
               "update_histograms": {}}
        for u in ups:    # keep the LATEST report carrying histograms
            c = u.content
            if c.get("param_histograms") or c.get("update_histograms"):
                out["iteration"] = c.get("iteration")
                out["param_histograms"] = c.get("param_histograms") or {}
                out["update_histograms"] = c.get("update_histograms") or {}
        return out

    def _flow_data(self, storage):
        """Network topology + latest activation stats — the flow-module
        payload (reference: `ui/module/flow/FlowIterationListener` network
        structure + per-layer activations). Nodes/edges come from the
        static report's config_json (MLN: layer chain; CG: vertex DAG)."""
        st = self._static(storage)
        nodes, edges = [], []
        cj = st.get("config_json")
        if cj:
            try:
                conf = json.loads(cj)
            except (json.JSONDecodeError, TypeError):
                conf = {}
            if "vertices" in conf:              # ComputationGraph
                for name in conf.get("network_inputs", []):
                    nodes.append({"name": name, "type": "Input"})
                order = conf.get("topological_order") or \
                    list(conf["vertices"])
                for name in order:
                    v = conf["vertices"].get(name) or {}
                    ltype = ((v.get("layer") or {}).get("@class")
                             or v.get("@class") or "?")
                    nodes.append({"name": name, "type": ltype})
                for name, ins in (conf.get("vertex_inputs") or {}).items():
                    for src in ins:
                        edges.append([src, name])
            elif "layers" in conf:              # MultiLayerNetwork chain
                nodes.append({"name": "input", "type": "Input"})
                prev = "input"
                for layer in conf["layers"]:
                    name = layer.get("name") or layer.get("@class")
                    nodes.append({"name": name,
                                  "type": layer.get("@class", "?")})
                    edges.append([prev, name])
                    prev = name
        acts, param_stats = {}, {}
        ups = self._updates(storage)
        for u in ups:
            if u.content.get("activation_stats"):
                acts = u.content["activation_stats"]
            if u.content.get("param_stats"):
                param_stats = u.content["param_stats"]
        return {"nodes": nodes, "edges": edges, "activations": acts,
                "param_stats": param_stats}

    def _flow_html(self, storage) -> str:
        """Server-side flow snapshot (no-JS fallback; the JS client
        replaces it with the heat-colored diagram)."""
        d = self._flow_data(storage)
        if not d["nodes"]:
            return "<div class=card>no network structure yet</div>"
        acts = d["activations"]
        rows = []
        for nd in d["nodes"]:
            a = acts.get(nd["name"]) or {}
            rows.append((nd["name"], nd["type"],
                         f"{a.get('mean', 0):.4g}" if a else "-",
                         f"{a.get('std', 0):.4g}" if a else "-"))
        from html import escape

        tbl = ComponentTable(
            title="Network flow (layers in forward order)",
            header=("layer", "type", "act mean", "act std"),
            rows=tuple(rows)).render()
        edges = ", ".join(
            f"{escape(a)}→{escape(b)}" for a, b in d["edges"])
        return (f"<div class=card>{tbl}"
                f"<div class=meta>edges: {edges}</div></div>")

    def _histogram_html(self, storage) -> str:
        d = self._histogram_data(storage)
        parts = []
        for kind, label in (("param_histograms", "parameters"),
                            ("update_histograms", "updates")):
            comps = [histogram_component(f"{n} ({label})", h)
                     for n, h in (d[kind] or {}).items()]
            if comps:
                parts.append(ComponentDiv(children=tuple(comps)).render())
        if not parts:
            return ("<div class=card>no histograms — construct "
                    "StatsListener(collect_histograms=True)</div>")
        return "<div class=card>" + "</div><div class=card>".join(parts) \
            + "</div>"

    def _system_data(self, storage):
        ups = self._updates(storage)
        out = {"iterations": [], "memory_rss_mb": [],
               "minibatches_per_second": [], "static": self._static(storage)}
        for u in ups:
            c = u.content
            out["iterations"].append(c.get("iteration"))
            out["memory_rss_mb"].append(c.get("memory_rss_mb"))
            out["minibatches_per_second"].append(
                c.get("minibatches_per_second"))
        return out

    def _tsne_data(self, storage):
        if storage is None:
            return {"x": [], "y": [], "labels": []}
        for sid in reversed(storage.list_session_ids()):
            if TSNE_TYPE_ID not in storage.list_type_ids(sid):
                continue
            for wid in storage.list_worker_ids(sid, TSNE_TYPE_ID):
                ups = storage.get_all_updates(sid, TSNE_TYPE_ID, wid)
                if ups:
                    return ups[-1].content
        return {"x": [], "y": [], "labels": []}

    # ------------------------------------------------- component building
    def _model_components(self, storage) -> ComponentDiv:
        """The model tab as a reusable component tree (this JSON is served
        at /train/model/components — the ui-components contract)."""
        data = self._model_data(storage)
        sections = []
        for name, d in data["layers"].items():
            charts: List[Any] = [ChartLine(
                title=f"{name}: norms",
                series_names=("param norm2", "update norm2"),
                x=(tuple(d["iterations"]), tuple(d["iterations"])),
                y=(tuple(v or 0.0 for v in d["param_norm"]),
                   tuple(v or 0.0 for v in d["update_norm"])))]
            ratios = [v for v in d["ratio"] if v is not None]
            if ratios:
                its = [i for i, v in zip(d["iterations"], d["ratio"])
                       if v is not None]
                charts.append(ChartLine(
                    title=f"{name}: update/param ratio",
                    series_names=("ratio",),
                    x=(tuple(its),), y=(tuple(ratios),)))
            if name in data["param_histograms"]:
                charts.append(histogram_component(
                    f"{name}: parameter histogram",
                    data["param_histograms"][name]))
            sections.append(DecoratorAccordion(
                title=name, children=tuple(charts),
                default_collapsed=True))
        for name, d in data["activations"].items():
            sections.append(DecoratorAccordion(
                title=f"activations: {name}", default_collapsed=True,
                children=(ChartLine(
                    title=f"{name}: activation mean / std",
                    series_names=("mean", "std"),
                    x=(tuple(d["iterations"]), tuple(d["iterations"])),
                    y=(tuple(v or 0.0 for v in d["mean"]),
                       tuple(v or 0.0 for v in d["std"]))),)))
        return ComponentDiv(children=tuple(sections))

    # ------------------------------------------------------------- pages
    def _overview_html(self, storage) -> str:
        o = self._overview(storage)
        parts = []
        if o["iterations"]:
            parts.append(ChartLine(
                title="Score vs iteration", series_names=("score",),
                x=(tuple(o["iterations"]),), y=(tuple(o["scores"]),),
                style=Style(width=920)).render())
        rows = []
        for k, v in (o.get("param_stats") or {}).items():
            u = (o.get("update_stats") or {}).get(k) or {}
            rows.append((k, f"{v.get('norm2', 0):.5g}",
                         f"{v.get('mean_magnitude', 0):.5g}",
                         f"{u.get('norm2', 0):.5g}" if u else "-"))
        parts.append(ComponentTable(
            title="Parameters (last report)",
            header=("parameter", "norm2", "mean magnitude", "update norm2"),
            rows=tuple(rows)).render())
        st = o.get("static") or {}
        mbs = o.get("minibatches_per_second")
        parts.append(
            f"<div class=meta>{len(o['iterations'])} reports; "
            f"{(mbs or 0):.2f} minibatches/s; "
            f"model {st.get('model_class', '-')}, "
            f"{st.get('num_params', '-')} params, "
            f"backend {st.get('backend', '-')}</div>")
        return "<div class=card>" + "</div><div class=card>".join(parts) + \
            "</div>"

    def _model_html(self, storage) -> str:
        comp = self._model_components(storage)
        if not comp.children:
            return "<div class=card>no model reports yet</div>"
        return f"<div class=card>{comp.render()}</div>"

    def _system_html(self, storage) -> str:
        d = self._system_data(storage)
        parts = []
        its = [i for i in d["iterations"] if i is not None]
        mem = [m or 0.0 for m in d["memory_rss_mb"]]
        if its and mem:
            parts.append(ChartLine(
                title="Host RSS (MB)", series_names=("rss_mb",),
                x=(tuple(its),), y=(tuple(mem),),
                style=Style(width=920)).render())
        rate = [r for r in d["minibatches_per_second"] if r is not None]
        if rate:
            parts.append(ChartLine(
                title="Minibatches / second",
                series_names=("mb/s",),
                x=(tuple(range(len(rate))),), y=(tuple(rate),),
                style=Style(width=920)).render())
        st = d.get("static") or {}
        rows = [("software", json.dumps(st.get("software") or {})),
                ("hardware", json.dumps(st.get("hardware") or {})),
                ("model", str(st.get("model_class")))]
        parts.append(ComponentTable(
            title="Environment", header=("key", "value"),
            rows=tuple(rows)).render())
        return "<div class=card>" + "</div><div class=card>".join(parts) + \
            "</div>"

    def _tsne_html(self, storage) -> str:
        d = self._tsne_data(storage)
        if not d.get("x"):
            return ("<div class=card>no embedding uploaded — use "
                    "UIServer.upload_tsne(points, labels)</div>")
        labels = d.get("labels") or [0] * len(d["x"])
        by_label: Dict[Any, list] = {}
        for x, y, l in zip(d["x"], d["y"], labels):
            by_label.setdefault(l, []).append((x, y))
        names, xs, ys = [], [], []
        for l, pts in sorted(by_label.items(), key=lambda kv: str(kv[0])):
            names.append(str(l))
            xs.append(tuple(p[0] for p in pts))
            ys.append(tuple(p[1] for p in pts))
        chart = ChartScatter(
            title="t-SNE embedding", series_names=tuple(names),
            x=tuple(xs), y=tuple(ys), style=Style(width=920, height=560))
        return f"<div class=card>{chart.render()}</div>"

    # --------------------------------------------------------------- POST
    def do_POST(self):
        """Remote-listener receiver + t-SNE upload. Reference:
        `RemoteReceiverModule.java` paired with PlayUIServer
        `enableRemoteListener():313`; tsne upload mirrors the reference
        tsne module's coordinate upload."""
        ui = self.server.ui
        path = self.path.rstrip("/")
        n = int(self.headers.get("Content-Length", 0))
        if path == "/tsne":
            # write path: gated like /remote (local callers use the
            # UIServer.upload_tsne API directly)
            if not ui.remote_enabled:
                return self._send(404, "remote receiver not enabled",
                                  "text/plain")
            try:
                body = json.loads(self.rfile.read(n))
                pts = list(zip(body["x"], body["y"]))
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                return self._send(400, f"bad tsne payload: {e}",
                                  "text/plain")
            ui.upload_tsne(pts, body.get("labels"))
            return self._send_json({"ok": True})
        if path != "/remote" or not ui.remote_enabled:
            return self._send(404, "remote receiver not enabled",
                              "text/plain")
        body = json.loads(self.rfile.read(n))
        rec = Persistable(**body["record"])
        if ui.storage is not None:
            if body.get("kind") == "static":
                ui.storage.put_static_info(rec)
            else:
                ui.storage.put_update(rec)
        self._send_json({"ok": True})

    # ------------------------------------------------------------ helpers
    def _send(self, code, body, ctype):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        if ctype.startswith("text/") and "charset" not in ctype:
            ctype += "; charset=utf-8"
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, obj):
        self._send(200, json.dumps(obj), "application/json")


class UIServer:
    """Reference: `PlayUIServer` — `getInstance()`, `attach(storage)`,
    `enableRemoteListener()`. Port 0 picks a free port (the reference
    defaults to 9000 via the ui.port property)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0):
        self.storage: Optional[StatsStorage] = None
        self.remote_enabled = False
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.ui = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def attach(self, storage: StatsStorage) -> None:
        self.storage = storage

    def detach(self, storage: StatsStorage) -> None:
        if self.storage is storage:
            self.storage = None

    def enable_remote_listener(self) -> None:
        self.remote_enabled = True
        if self.storage is None:
            self.storage = StatsStorage()

    def upload_tsne(self, points, labels=None,
                    session_id: str = "tsne") -> None:
        """Publish a 2-D embedding to the t-SNE viewer (reference:
        `ui/module/tsne/` coordinate upload). `points`: [N, 2] array or
        list of (x, y); `labels`: optional per-point labels for coloring."""
        if self.storage is None:
            self.storage = StatsStorage()
        pts = [(float(p[0]), float(p[1])) for p in points]
        content = {"x": [p[0] for p in pts], "y": [p[1] for p in pts],
                   "labels": (None if labels is None
                              else [str(l) for l in labels])}
        self.storage.put_update(Persistable(
            session_id, TSNE_TYPE_ID, "tsne", time.time(), content))

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteStatsRouter(StatsStorageRouter):
    """HTTP-POST router to a remote UIServer. Reference:
    `impl/RemoteUIStatsStorageRouter.java:33` (posts records, silently
    retries/drops on failure so training never blocks on the UI)."""

    def __init__(self, url: str, *, timeout: float = 2.0,
                 raise_on_error: bool = False):
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self.raise_on_error = raise_on_error

    def _post(self, kind: str, record: Persistable) -> None:
        import dataclasses as dc
        body = json.dumps({"kind": kind,
                           "record": dc.asdict(record)}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=self.timeout).read()
        except Exception:
            if self.raise_on_error:
                raise

    def put_static_info(self, record: Persistable) -> None:
        self._post("static", record)

    def put_update(self, record: Persistable) -> None:
        self._post("update", record)
