"""StatsListener — per-iteration training statistics capture.

Reference parity: `deeplearning4j-ui-model/.../ui/stats/BaseStatsListener.java`
(`iterationDone:297` gathers score, param/update histograms + mean magnitudes,
minibatch/example rates, memory, every `listenerFrequency` iterations, and
routes an initialization report + update reports into a `StatsStorageRouter`).

TPU redesign: all per-layer statistics for one report are computed in ONE
jitted reduction over the parameter pytree (a single device program, one
host transfer), instead of the reference's per-array host loops. Update
stats come from parameter deltas between reports — equivalent information
to the reference's update histograms without forcing the train step to
materialize gradients on host every iteration (which would stall the
async dispatch pipeline).
"""

from __future__ import annotations

import json
import resource
import time
import uuid
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optim.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import Persistable, StatsStorageRouter

TYPE_ID = "StatsListener"  # reference: BaseStatsListener.TYPE_ID:45


@jax.jit
def _tree_stats(tree):
    """Per-leaf {mean, std, min, max, norm2, histogram} in one XLA program."""
    def leaf(x):
        x = x.astype(jnp.float32)
        return {
            "mean": jnp.mean(x),
            "std": jnp.std(x),
            "min": jnp.min(x),
            "max": jnp.max(x),
            "norm2": jnp.linalg.norm(x.ravel()),
            "mean_magnitude": jnp.mean(jnp.abs(x)),
        }
    return jax.tree_util.tree_map(leaf, tree)


def _histogram(x: np.ndarray, bins: int = 20) -> Dict[str, Any]:
    counts, edges = np.histogram(x, bins=bins)
    return {"counts": counts.tolist(),
            "min": float(edges[0]), "max": float(edges[-1])}


class StatsListener(TrainingListener):
    """Reference: `BaseStatsListener` + its concrete
    `ui/stats/StatsListener.java`; constructor mirrors
    `BaseStatsListener(StatsStorageRouter, listenerFrequency):117`."""

    def __init__(self, router: StatsStorageRouter, frequency: int = 1, *,
                 session_id: Optional[str] = None, worker_id: str = "local",
                 collect_histograms: bool = False, histogram_bins: int = 20,
                 collect_activations: bool = False,
                 activation_examples: int = 32):
        self.router = router
        self.frequency = max(frequency, 1)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self.collect_activations = collect_activations
        self.activation_examples = activation_examples
        self._init_done = False
        self._count = 0
        self._last_report_time: Optional[float] = None
        self._last_params: Optional[Dict[str, np.ndarray]] = None
        self._iter_since_report = 0

    # ---------------------------------------------------------------- hooks
    def on_fit_start(self, model) -> None:
        if not self._init_done:
            self._do_init(model)

    def iteration_done(self, model, iteration: int, epoch: int,
                       score) -> None:
        self._count += 1
        self._iter_since_report += 1
        if self._count % self.frequency:
            return
        if not self._init_done:
            self._do_init(model)
        now = time.time()
        report: Dict[str, Any] = {
            "iteration": int(iteration),
            "epoch": int(epoch),
            "score": float(score),
            "timestamp": now,
        }
        # rates (reference: updateExamplesMinibatchesCounts:695 + rate calc)
        if self._last_report_time is not None:
            dt = max(now - self._last_report_time, 1e-9)
            report["minibatches_per_second"] = self._iter_since_report / dt
        self._last_report_time = now
        self._iter_since_report = 0

        params = getattr(model, "params_tree", None)
        if params is not None:
            stats = jax.device_get(_tree_stats(params))
            report["param_stats"] = _to_plain(stats)
            host = jax.device_get(params)
            flatcur, _ = jax.tree_util.tree_flatten(host)
            if self._last_params is not None and len(self._last_params) == \
                    len(flatcur):
                upd = [np.asarray(c) - p
                       for c, p in zip(flatcur, self._last_params)]
                names = _leaf_names(params)
                report["update_stats"] = {
                    n: {"mean_magnitude": float(np.mean(np.abs(u))),
                        "norm2": float(np.linalg.norm(u.ravel()))}
                    for n, u in zip(names, upd)
                }
                if self.collect_histograms:
                    # gradient/update histograms — the HistogramModule's
                    # second panel (reference: BaseStatsListener update
                    # histogram collection)
                    report["update_histograms"] = {
                        n: _histogram(np.asarray(u).ravel(),
                                      self.histogram_bins)
                        for n, u in zip(names, upd)
                    }
            if self.collect_histograms:
                names = _leaf_names(params)
                report["param_histograms"] = {
                    n: _histogram(np.asarray(a).ravel(), self.histogram_bins)
                    for n, a in zip(names, flatcur)
                }
            self._last_params = [np.asarray(a) for a in flatcur]

        # activation stats (reference: BaseStatsListener activation
        # mean-magnitude/histogram collection via onForwardPass) — one
        # extra forward on a slice of the last training batch, opt-in.
        if self.collect_activations:
            feats = getattr(model, "_last_features", None)
            ff = getattr(model, "feed_forward", None)
            if feats is not None and ff is not None:
                sample = feats[:self.activation_examples]
                acts = ff(sample)
                layer_names = [l.name for l in model.conf.layers]
                report["activation_stats"] = {
                    n: {"mean": float(np.mean(a)),
                        "std": float(np.std(a)),
                        "mean_magnitude": float(np.mean(np.abs(a)))}
                    for n, a in zip(layer_names,
                                    (np.asarray(a) for a in acts))
                }

        # memory (reference: system/JVM memory in the init+update reports)
        report["memory_rss_mb"] = (
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0)
        # device-truth counterpart: per-device HBM in-use/peak/limit plus
        # live-array counts (None entries where the backend reports
        # nothing, e.g. CPU) — the DL4J UI showed JVM+offheap, ours
        # shows host RSS + device memory side by side
        from deeplearning4j_tpu.observe.devicemon import (
            device_memory_summary,
        )
        dm = device_memory_summary()
        if dm is not None:
            report["device_memory"] = dm

        self.router.put_update(Persistable(
            self.session_id, TYPE_ID, self.worker_id, now, report))

    # ----------------------------------------------------------------- init
    def _do_init(self, model) -> None:
        """Reference: `BaseStatsListener.doInit:560` — session metadata,
        software/hardware info, model config + param counts."""
        conf_json = None
        conf = getattr(model, "conf", None)
        if conf is not None and hasattr(conf, "to_json"):
            try:
                conf_json = conf.to_json()
            except Exception:
                conf_json = None
        backend = jax.default_backend()
        info = {
            "model_class": type(model).__name__,
            "config_json": conf_json,
            "num_params": int(getattr(model, "num_params", lambda: 0)() or 0),
            "software": {"jax_version": jax.__version__,
                         "backend": backend},
            "hardware": {"num_devices": jax.device_count(),
                         # hardware metadata for the dashboard, not placement
                         "device_kind": jax.devices()[0].device_kind},  # graft: allow(GL501): UI reads device kind for display only
            "timestamp": time.time(),
        }
        self.router.put_static_info(Persistable(
            self.session_id, TYPE_ID, self.worker_id, time.time(), info))
        self._init_done = True

    def clone(self, worker_id: Optional[str] = None) -> "StatsListener":
        """Per-replica copy for multi-worker training (the reference's
        ParallelWrapper clones listeners per Trainer): SAME session, distinct
        worker id, fresh accumulation state."""
        if worker_id is None:
            worker_id = f"{self.worker_id}-{uuid.uuid4().hex[:6]}"
        return StatsListener(self.router, self.frequency,
                             session_id=self.session_id,
                             worker_id=worker_id,
                             collect_histograms=self.collect_histograms,
                             histogram_bins=self.histogram_bins,
                             collect_activations=self.collect_activations,
                             activation_examples=self.activation_examples)


def _leaf_names(tree) -> list:
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path) for path, _ in paths]


def _to_plain(tree) -> Dict[str, Dict[str, float]]:
    names = _leaf_names(tree)
    flat, _ = jax.tree_util.tree_flatten(tree)
    # tree has dict leaves of scalars; regroup: flatten gave us scalars in
    # stat-name order per leaf
    out: Dict[str, Dict[str, float]] = {}
    stat_keys = ["max", "mean", "mean_magnitude", "min", "norm2", "std"]
    # names include the stat suffix (leaf dicts flattened too); rebuild:
    grouped: Dict[str, Dict[str, float]] = {}
    for n, v in zip(names, flat):
        *prefix, stat = n.split("/")
        grouped.setdefault("/".join(prefix), {})[stat] = float(v)
    out.update(grouped)
    return out
