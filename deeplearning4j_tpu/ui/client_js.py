"""Browser polling client for the training UI — served at /js/app.js.

Reference parity: the Play UI's dashboards poll JSON endpoints from
JavaScript and redraw without page reloads
(`deeplearning4j-play/src/main/resources/.../js/train/overview.js`,
`.../module/histogram/`, `.../module/flow/` — charting via jquery/flot).
TPU redesign: one dependency-free script; charts are generated as SVG
strings from the same JSON the server exposes under /train/*, so a page
left open live-follows a training run. Each HTML page carries
`<body data-page=...>`; the script polls the page's endpoint every 2 s
and swaps the #live container.
"""

APP_JS = r"""
"use strict";
(function () {
  var PAGE = document.body.dataset.page || "";
  var INTERVAL = 2000;
  var COLORS = ["#1976d2", "#e53935", "#43a047", "#fb8c00", "#8e24aa",
                "#00897b", "#6d4c41"];

  function esc(s) {
    return String(s).replace(/[&<>"]/g, function (c) {
      return {"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c];
    });
  }

  function finitePairs(xs, ys) {
    var out = [];
    for (var i = 0; i < ys.length; i++) {
      var x = xs[i], y = ys[i];
      if (x == null || y == null || !isFinite(x) || !isFinite(y)) continue;
      out.push([x, y]);
    }
    return out;
  }

  function lineChart(title, names, xss, yss, w, h) {
    w = w || 900; h = h || 220;
    var xmin = Infinity, xmax = -Infinity, ymin = Infinity, ymax = -Infinity;
    var series = [];
    for (var s = 0; s < yss.length; s++) {
      var pts = finitePairs(xss[s], yss[s]);
      series.push(pts);
      for (var i = 0; i < pts.length; i++) {
        xmin = Math.min(xmin, pts[i][0]); xmax = Math.max(xmax, pts[i][0]);
        ymin = Math.min(ymin, pts[i][1]); ymax = Math.max(ymax, pts[i][1]);
      }
    }
    if (xmin === Infinity) return "";
    if (xmax === xmin) xmax = xmin + 1;
    if (ymax === ymin) ymax = ymin + 1;
    var L = 58, R = 12, T = 26, B = 24, iw = w - L - R, ih = h - T - B;
    var X = function (x) { return L + (x - xmin) / (xmax - xmin) * iw; };
    var Y = function (y) { return T + ih - (y - ymin) / (ymax - ymin) * ih; };
    var o = '<svg width="' + w + '" height="' + h +
            '" xmlns="http://www.w3.org/2000/svg">';
    o += '<text x="' + (w / 2) + '" y="15" text-anchor="middle"' +
         ' font-size="13" font-weight="bold">' + esc(title) + "</text>";
    var i, v;
    for (i = 0; i <= 4; i++) {
      v = ymin + (ymax - ymin) * i / 4;
      o += '<line x1="' + L + '" y1="' + Y(v) + '" x2="' + (L + iw) +
           '" y2="' + Y(v) + '" stroke="#eee"/>';
      o += '<text x="' + (L - 5) + '" y="' + (Y(v) + 4) +
           '" text-anchor="end" font-size="10" fill="#666">' +
           v.toPrecision(3) + "</text>";
    }
    for (i = 0; i <= 4; i++) {
      v = xmin + (xmax - xmin) * i / 4;
      o += '<text x="' + X(v) + '" y="' + (T + ih + 15) +
           '" text-anchor="middle" font-size="10" fill="#666">' +
           v.toPrecision(3) + "</text>";
    }
    o += '<line x1="' + L + '" y1="' + T + '" x2="' + L + '" y2="' +
         (T + ih) + '" stroke="#999"/>';
    o += '<line x1="' + L + '" y1="' + (T + ih) + '" x2="' + (L + iw) +
         '" y2="' + (T + ih) + '" stroke="#999"/>';
    for (s = 0; s < series.length; s++) {
      var p = series[s].map(function (q) {
        return X(q[0]).toFixed(1) + "," + Y(q[1]).toFixed(1);
      }).join(" ");
      var col = COLORS[s % COLORS.length];
      o += '<polyline fill="none" stroke="' + col +
           '" stroke-width="1.5" points="' + p + '"/>';
      o += '<rect x="' + (L + 8 + s * 150) + '" y="' + (T - 16) +
           '" width="10" height="10" fill="' + col + '"/>' +
           '<text x="' + (L + 21 + s * 150) + '" y="' + (T - 7) +
           '" font-size="11">' + esc(names[s]) + "</text>";
    }
    return o + "</svg>";
  }

  function histChart(title, hist, w, h) {
    w = w || 430; h = h || 170;
    if (!hist || !hist.counts || !hist.counts.length) return "";
    var counts = hist.counts;
    var L = 40, R = 8, T = 24, B = 20, iw = w - L - R, ih = h - T - B;
    var cmax = Math.max.apply(null, counts) || 1;
    var n = counts.length, bw = iw / n;
    var o = '<svg width="' + w + '" height="' + h +
            '" xmlns="http://www.w3.org/2000/svg">';
    o += '<text x="' + (w / 2) + '" y="14" text-anchor="middle"' +
         ' font-size="12" font-weight="bold">' + esc(title) + "</text>";
    for (var i = 0; i < n; i++) {
      var bh = counts[i] / cmax * ih;
      o += '<rect x="' + (L + i * bw + 1).toFixed(1) + '" y="' +
           (T + ih - bh).toFixed(1) + '" width="' + (bw - 2).toFixed(1) +
           '" height="' + bh.toFixed(1) + '" fill="#5c6bc0"/>';
    }
    o += '<line x1="' + L + '" y1="' + (T + ih) + '" x2="' + (L + iw) +
         '" y2="' + (T + ih) + '" stroke="#999"/>';
    if (hist.min != null && hist.max != null) {
      o += '<text x="' + L + '" y="' + (h - 5) + '" font-size="10"' +
           ' fill="#666">' + Number(hist.min).toPrecision(3) + "</text>";
      o += '<text x="' + (L + iw) + '" y="' + (h - 5) +
           '" text-anchor="end" font-size="10" fill="#666">' +
           Number(hist.max).toPrecision(3) + "</text>";
    }
    return o + "</svg>";
  }

  function card(inner) { return '<div class="card">' + inner + "</div>"; }

  function table(title, header, rows) {
    var o = '<table class="uic"><caption style="font-weight:bold;' +
            'font-size:13px">' + esc(title) + "</caption><tr>";
    header.forEach(function (hh) { o += "<th>" + esc(hh) + "</th>"; });
    o += "</tr>";
    rows.forEach(function (r) {
      o += "<tr>" + r.map(function (c) {
        return "<td>" + esc(c) + "</td>";
      }).join("") + "</tr>";
    });
    return o + "</table>";
  }

  function fmt(v) {
    if (v == null) return "-";
    return (typeof v === "number") ? v.toPrecision(4) : String(v);
  }

  // ------------------------------------------------------ page renderers
  function renderOverview(d) {
    var parts = [];
    if (d.iterations && d.iterations.length) {
      parts.push(card(lineChart("Score vs iteration", ["score"],
                                [d.iterations], [d.scores])));
    }
    var rows = [];
    var ps = d.param_stats || {}, us = d.update_stats || {};
    Object.keys(ps).forEach(function (k) {
      rows.push([k, fmt(ps[k].norm2), fmt(ps[k].mean_magnitude),
                 fmt((us[k] || {}).norm2)]);
    });
    if (rows.length) {
      parts.push(card(table("Parameters (last report)",
                            ["parameter", "norm2", "mean magnitude",
                             "update norm2"], rows)));
    }
    var st = d.static || {};
    parts.push('<div class="meta">' + (d.iterations || []).length +
               " reports; " + fmt(d.minibatches_per_second) +
               " minibatches/s; model " + esc(st.model_class || "-") +
               ", " + esc(st.num_params || "-") + " params</div>");
    return parts.join("");
  }

  function renderModel(d) {
    var parts = [];
    Object.keys(d.layers || {}).forEach(function (name) {
      var L = d.layers[name];
      var inner = lineChart(name + ": norms",
                            ["param norm2", "update norm2"],
                            [L.iterations, L.iterations],
                            [L.param_norm, L.update_norm]);
      inner += lineChart(name + ": update/param ratio", ["ratio"],
                         [L.iterations], [L.ratio], 900, 150);
      var h = (d.param_histograms || {})[name];
      if (h) inner += histChart(name + ": parameter histogram", h);
      parts.push(card(inner));
    });
    Object.keys(d.activations || {}).forEach(function (name) {
      var A = d.activations[name];
      parts.push(card(lineChart("activations " + name + ": mean / std",
                                ["mean", "std"],
                                [A.iterations, A.iterations],
                                [A.mean, A.std], 900, 170)));
    });
    return parts.join("") || '<div class="card">no model reports yet</div>';
  }

  function renderHistogram(d) {
    var parts = [];
    [["param_histograms", "parameters"],
     ["update_histograms", "updates"]].forEach(function (kind) {
      var hs = d[kind[0]] || {};
      var inner = "";
      Object.keys(hs).forEach(function (name) {
        inner += histChart(name + " (" + kind[1] + ")", hs[name]);
      });
      if (inner) parts.push(card("<h3>" + kind[1] + "</h3>" + inner));
    });
    if (!parts.length) {
      return '<div class="card">no histograms — construct ' +
             "StatsListener(collect_histograms=True)</div>";
    }
    return '<div class="meta">iteration ' + fmt(d.iteration) + "</div>" +
           parts.join("");
  }

  function renderFlow(d) {
    var nodes = d.nodes || [];
    if (!nodes.length) {
      return '<div class="card">no network structure yet</div>';
    }
    var bw = 210, bh = 46, gap = 26, w = 900;
    var x0 = 40, y = 16;
    var pos = {};
    var o = "";
    var maxMag = 1e-12;
    nodes.forEach(function (nd) {
      var a = (d.activations || {})[nd.name];
      if (a && a.mean_magnitude) maxMag = Math.max(maxMag, a.mean_magnitude);
    });
    nodes.forEach(function (nd) {
      pos[nd.name] = y;
      var a = (d.activations || {})[nd.name];
      var heat = a ? Math.min(1, (a.mean_magnitude || 0) / maxMag) : 0;
      var fill = a ? "rgba(25,118,210," + (0.12 + 0.5 * heat).toFixed(2) +
                 ")" : "#f5f5f5";
      o += '<rect x="' + x0 + '" y="' + y + '" width="' + bw +
           '" height="' + bh + '" rx="6" fill="' + fill +
           '" stroke="#90a4ae"/>';
      o += '<text x="' + (x0 + 10) + '" y="' + (y + 18) +
           '" font-size="12" font-weight="bold">' + esc(nd.name) +
           "</text>";
      o += '<text x="' + (x0 + 10) + '" y="' + (y + 34) +
           '" font-size="10" fill="#555">' + esc(nd.type) +
           (a ? "  act mean " + fmt(a.mean) + " std " + fmt(a.std) : "") +
           "</text>";
      y += bh + gap;
    });
    (d.edges || []).forEach(function (e) {
      var ya = pos[e[0]], yb = pos[e[1]];
      if (ya == null || yb == null) return;
      var xa = x0 + bw / 2, x1 = ya + bh, y2 = yb;
      if (y2 - x1 <= gap + 1) {
        o += '<line x1="' + xa + '" y1="' + x1 + '" x2="' + xa +
             '" y2="' + y2 + '" stroke="#607d8b" marker-end="url(#arr)"/>';
      } else {   // skip connection: arc on the right
        var xr = x0 + bw + 40;
        o += '<path d="M ' + (x0 + bw) + " " + (x1 - bh / 2) + " C " + xr +
             " " + (x1 - bh / 2) + ", " + xr + " " + (y2 + bh / 2) + ", " +
             (x0 + bw) + " " + (y2 + bh / 2) +
             '" fill="none" stroke="#607d8b" marker-end="url(#arr)"/>';
      }
    });
    var svg = '<svg width="' + w + '" height="' + (y + 4) +
              '" xmlns="http://www.w3.org/2000/svg"><defs>' +
              '<marker id="arr" markerWidth="8" markerHeight="8" refX="6"' +
              ' refY="3" orient="auto"><path d="M0,0 L6,3 L0,6 z"' +
              ' fill="#607d8b"/></marker></defs>' + o + "</svg>";
    return card("<h3>Network flow (activation heat)</h3>" + svg);
  }

  function renderSystem(d) {
    var parts = [];
    var its = d.iterations || [];
    if (its.length) {
      parts.push(card(lineChart("Host RSS (MB)", ["rss_mb"], [its],
                                [d.memory_rss_mb])));
      parts.push(card(lineChart("Minibatches / second", ["mb/s"], [its],
                                [d.minibatches_per_second], 900, 170)));
    }
    var st = d.static || {};
    parts.push(card(table("Environment", ["key", "value"],
                          [["software", JSON.stringify(st.software || {})],
                           ["hardware", JSON.stringify(st.hardware || {})],
                           ["model", String(st.model_class)]])));
    return parts.join("");
  }

  function renderTsne(d) {
    if (!d.x || !d.x.length) {
      return '<div class="card">no embedding uploaded</div>';
    }
    var labels = d.labels || d.x.map(function () { return "0"; });
    var xmin = Math.min.apply(null, d.x), xmax = Math.max.apply(null, d.x);
    var ymin = Math.min.apply(null, d.y), ymax = Math.max.apply(null, d.y);
    if (xmax === xmin) xmax = xmin + 1;
    if (ymax === ymin) ymax = ymin + 1;
    var w = 900, h = 540, L = 20, T = 20;
    var uniq = [];
    labels.forEach(function (l) {
      if (uniq.indexOf(l) < 0) uniq.push(l);
    });
    var o = '<svg width="' + w + '" height="' + h +
            '" xmlns="http://www.w3.org/2000/svg">';
    for (var i = 0; i < d.x.length; i++) {
      var cx = L + (d.x[i] - xmin) / (xmax - xmin) * (w - 2 * L);
      var cy = T + (h - 2 * T) - (d.y[i] - ymin) / (ymax - ymin) *
               (h - 2 * T);
      var col = COLORS[uniq.indexOf(labels[i]) % COLORS.length];
      o += '<circle cx="' + cx.toFixed(1) + '" cy="' + cy.toFixed(1) +
           '" r="2.5" fill="' + col + '" fill-opacity="0.7"/>';
    }
    return card(o + "</svg>");
  }

  var ROUTES = {
    overview: ["/train/overview", renderOverview],
    model: ["/train/model", renderModel],
    histogram: ["/train/histogram", renderHistogram],
    flow: ["/train/flow", renderFlow],
    system: ["/train/system", renderSystem],
    tsne: ["/tsne", renderTsne]
  };

  function tick() {
    var route = ROUTES[PAGE];
    if (!route) return;
    fetch(route[0], {cache: "no-store"}).then(function (r) {
      if (!r.ok) throw new Error(r.status);
      return r.json();
    }).then(function (d) {
      var live = document.getElementById("live");
      if (live) live.innerHTML = route[1](d);
      var st = document.getElementById("status");
      if (st) {
        st.textContent = "live · updated " +
                         new Date().toLocaleTimeString();
      }
      setTimeout(tick, INTERVAL);
    }).catch(function () {
      var st = document.getElementById("status");
      if (st) st.textContent = "disconnected · retrying…";
      setTimeout(tick, INTERVAL * 2);
    });
  }
  tick();
})();
"""
