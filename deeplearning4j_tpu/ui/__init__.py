"""Training observability: stats capture → storage → web dashboard.

Reference parity: `deeplearning4j-ui-parent/` — `BaseStatsListener`
(ui-model), the `StatsStorage`/`StatsStorageRouter` API
(`deeplearning4j-core/.../api/storage/StatsStorage.java`), in-memory/file
storage impls, the Play UI server (`ui/play/PlayUIServer.java`) and the
remote stats router/receiver
(`core/.../impl/RemoteUIStatsStorageRouter.java` +
`ui/module/remote/RemoteReceiverModule.java`).
"""

from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage, InMemoryStatsStorage, Persistable, StatsStorage,
    StatsStorageEvent, StatsStorageRouter,
)
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.server import RemoteStatsRouter, UIServer
from deeplearning4j_tpu.ui.components import (
    ChartHistogram, ChartHorizontalBar, ChartLine, ChartScatter,
    ChartStackedArea, ChartTimeline, Component, ComponentDiv,
    ComponentTable, ComponentText, DecoratorAccordion, Style,
)

__all__ = [
    "FileStatsStorage", "InMemoryStatsStorage", "Persistable",
    "StatsStorage", "StatsStorageEvent", "StatsStorageRouter",
    "StatsListener", "RemoteStatsRouter", "UIServer",
    "Component", "ChartLine", "ChartHistogram", "ChartScatter",
    "ChartHorizontalBar", "ChartStackedArea", "ChartTimeline",
    "ComponentDiv", "ComponentTable", "ComponentText",
    "DecoratorAccordion", "Style",
]
