"""UI internationalization layer.

Reference parity: `ui/i18n/I18N.java` + `DefaultI18N.java` (singleton,
`getMessage(key)` / `getMessage(langCode, key)`, current-language state,
"en" fallback when a key is missing in the selected language) and the
`dl4j_i18n/train.<lang>` property resources. The reference loads
`key=value` property files per language from the classpath; here the
same key naming (`train.nav.*`, `train.pagetitle`, ...) is served from
in-module tables, and `load_properties` ingests external `key=value`
text for user-supplied languages — the DEFAULT_I8N_RESOURCES_DIR seam.
"""

from __future__ import annotations

from typing import Dict, Optional

DEFAULT_LANGUAGE = "en"
FALLBACK_LANGUAGE = "en"

# Page-chrome messages for the six languages the reference ships
# (dl4j_i18n/train.{de,en,ja,ko,ru,zh}); keys follow the reference's
# naming so Keras-era muscle memory (and tests) transfer.
_MESSAGES: Dict[str, Dict[str, str]] = {
    "en": {
        "train.pagetitle": "DL4J-TPU Training UI",
        "train.nav.overview": "Overview",
        "train.nav.model": "Model",
        "train.nav.system": "System",
        "train.nav.histogram": "Histograms",
        "train.nav.flow": "Flow",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "Activations",
        "train.nav.language": "Language",
        "train.session.label": "Session",
        "train.session.worker.label": "Worker",
        "train.overview.chart.scoreTitle": "Score vs. Iteration",
        "train.activations.title": "Convolutional layer activations",
    },
    "de": {
        "train.pagetitle": "DL4J-TPU Trainings-UI",
        "train.nav.overview": "Übersicht",
        "train.nav.model": "Modell",
        "train.nav.system": "System",
        "train.nav.histogram": "Histogramme",
        "train.nav.flow": "Fluss",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "Aktivierungen",
        "train.nav.language": "Sprache",
        "train.session.label": "Sitzung",
        "train.session.worker.label": "Arbeiter",
        "train.overview.chart.scoreTitle": "Score pro Iteration",
        "train.activations.title": "Aktivierungen der Faltungsschichten",
    },
    "ja": {
        "train.pagetitle": "DL4J-TPU トレーニングUI",
        "train.nav.overview": "概要",
        "train.nav.model": "モデル",
        "train.nav.system": "システム",
        "train.nav.histogram": "ヒストグラム",
        "train.nav.flow": "フロー",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "活性化",
        "train.nav.language": "言語",
        "train.session.label": "セッション",
        "train.session.worker.label": "ワーカー",
        "train.overview.chart.scoreTitle": "スコア対反復",
        "train.activations.title": "畳み込み層の活性化",
    },
    "ko": {
        "train.pagetitle": "DL4J-TPU 트레이닝 UI",
        "train.nav.overview": "개요",
        "train.nav.model": "모델",
        "train.nav.system": "시스템",
        "train.nav.histogram": "히스토그램",
        "train.nav.flow": "플로우",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "활성화",
        "train.nav.language": "언어",
        "train.session.label": "세션",
        "train.session.worker.label": "워커",
        "train.overview.chart.scoreTitle": "반복별 점수",
        "train.activations.title": "합성곱 계층 활성화",
    },
    "ru": {
        "train.pagetitle": "DL4J-TPU интерфейс обучения",
        "train.nav.overview": "Обзор",
        "train.nav.model": "Модель",
        "train.nav.system": "Система",
        "train.nav.histogram": "Гистограммы",
        "train.nav.flow": "Поток",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "Активации",
        "train.nav.language": "Язык",
        "train.session.label": "Сессия",
        "train.session.worker.label": "Воркер",
        "train.overview.chart.scoreTitle": "Оценка по итерациям",
        "train.activations.title": "Активации сверточных слоев",
    },
    "zh": {
        "train.pagetitle": "DL4J-TPU 训练界面",
        "train.nav.overview": "概览",
        "train.nav.model": "模型",
        "train.nav.system": "系统",
        "train.nav.histogram": "直方图",
        "train.nav.flow": "流程",
        "train.nav.tsne": "t-SNE",
        "train.nav.activations": "激活",
        "train.nav.language": "语言",
        "train.session.label": "会话",
        "train.session.worker.label": "工作器",
        "train.overview.chart.scoreTitle": "每次迭代的得分",
        "train.activations.title": "卷积层激活",
    },
}


class DefaultI18N:
    """Singleton i18n service (reference: `DefaultI18N.getInstance()`)."""

    _instance: Optional["DefaultI18N"] = None

    def __init__(self):
        self._messages: Dict[str, Dict[str, str]] = {
            lang: dict(table) for lang, table in _MESSAGES.items()
        }
        self._current = DEFAULT_LANGUAGE

    @classmethod
    def get_instance(cls) -> "DefaultI18N":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # ---- reference I18N interface ----
    def get_message(self, key: str, lang: Optional[str] = None) -> str:
        """getMessage(key) / getMessage(langCode, key): selected language,
        then the "en" fallback, then the key itself (so a missing
        translation degrades visibly but harmlessly)."""
        lang = lang or self._current
        for table in (self._messages.get(lang),
                      self._messages.get(FALLBACK_LANGUAGE)):
            if table and key in table:
                return table[key]
        return key

    def get_default_language(self) -> str:
        return self._current

    def set_default_language(self, lang: str) -> None:
        self._current = lang

    def languages(self):
        return sorted(self._messages)

    def load_properties(self, lang: str, text: str) -> None:
        """Ingest a `key=value` properties blob for a language — the
        analogue of dropping a `train.<lang>` file into
        DEFAULT_I8N_RESOURCES_DIR."""
        table = self._messages.setdefault(lang, {})
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, _, v = line.partition("=")
            table[k.strip()] = v.strip()


def i18n() -> DefaultI18N:
    return DefaultI18N.get_instance()
