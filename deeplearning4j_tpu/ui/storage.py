"""Stats storage: pub/sub persistence for training statistics.

Reference parity: `deeplearning4j-core/.../api/storage/StatsStorage.java`
(session/type/worker IDs, static info vs updates, listener registration;
the interface extends `StatsStorageRouter.java` so every storage is also a
sink), `Persistable.java` (timestamped records), and the impls in
`deeplearning4j-ui-model/.../storage/` (InMemoryStatsStorage = map-backed,
FileStatsStorage = MapDB file — here an append-only JSONL file that is
replayed on open).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class Persistable:
    """One timestamped record. Reference: `api/storage/Persistable.java`
    (getSessionID/getTypeID/getWorkerID/getTimeStamp + serialization)."""

    session_id: str
    type_id: str
    worker_id: str
    timestamp: float
    content: Dict[str, Any]

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "Persistable":
        return cls(**json.loads(s))


@dataclasses.dataclass
class StatsStorageEvent:
    """Pub/sub notification. Reference: `api/storage/StatsStorageEvent.java`
    (NewSessionID / NewTypeID / NewWorkerID / PostUpdate)."""

    event_type: str  # "new_session" | "new_worker" | "post_update" | "post_static"
    session_id: str
    type_id: str
    worker_id: str
    timestamp: float


class StatsStorageRouter:
    """Write-side interface. Reference:
    `api/storage/StatsStorageRouter.java` (putStaticInfo/putUpdate)."""

    def put_static_info(self, record: Persistable) -> None:
        raise NotImplementedError

    def put_update(self, record: Persistable) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Readable storage + listener registry. Reference:
    `api/storage/StatsStorage.java:30` — every storage is also a router."""

    def __init__(self):
        self._static: Dict[Tuple[str, str, str], Persistable] = {}
        self._updates: Dict[Tuple[str, str, str], List[Persistable]] = {}
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = threading.RLock()

    # ------------------------------------------------------------- writes
    def put_static_info(self, record: Persistable) -> None:
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            new_session = not any(
                k[0] == record.session_id for k in
                list(self._static) + list(self._updates))
            self._static[key] = record
        self._persist("static", record)
        if new_session:
            self._emit("new_session", record)
        self._emit("post_static", record)

    def put_update(self, record: Persistable) -> None:
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            self._updates.setdefault(key, []).append(record)
        self._persist("update", record)
        self._emit("post_update", record)

    # -------------------------------------------------------------- reads
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in
                           list(self._static) + list(self._updates)})

    def list_type_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[1] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def list_worker_ids(self, session_id: str,
                        type_id: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted({k[2] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id
                           and (type_id is None or k[1] == type_id)})

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[Persistable]:
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[Persistable]:
        with self._lock:
            ups = self._updates.get((session_id, type_id, worker_id))
            return ups[-1] if ups else None

    def get_all_updates(self, session_id: str, type_id: str,
                        worker_id: str) -> List[Persistable]:
        with self._lock:
            return list(self._updates.get(
                (session_id, type_id, worker_id), []))

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, ts: float) -> List[Persistable]:
        """Reference: `StatsStorage.getAllUpdatesAfter`."""
        return [u for u in self.get_all_updates(session_id, type_id,
                                                worker_id)
                if u.timestamp > ts]

    def num_updates(self, session_id: str, type_id: str,
                    worker_id: str) -> int:
        with self._lock:
            return len(self._updates.get(
                (session_id, type_id, worker_id), []))

    # ---------------------------------------------------------- listeners
    def register_stats_storage_listener(
            self, fn: Callable[[StatsStorageEvent], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def deregister_stats_storage_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _emit(self, event_type: str, r: Persistable) -> None:
        ev = StatsStorageEvent(event_type, r.session_id, r.type_id,
                               r.worker_id, r.timestamp)
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(ev)

    # -------------------------------------------------------- persistence
    def _persist(self, kind: str, record: Persistable) -> None:
        pass  # in-memory: nothing to do

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """Reference: `ui-model/.../storage/InMemoryStatsStorage.java`."""


class FileStatsStorage(StatsStorage):
    """Append-only JSONL-file storage, replayed on open. Reference:
    `ui-model/.../storage/FileStatsStorage.java` (MapDB-backed there)."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._file = None
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    rec = Persistable(**obj["record"])
                    key = (rec.session_id, rec.type_id, rec.worker_id)
                    if obj["kind"] == "static":
                        self._static[key] = rec
                    else:
                        self._updates.setdefault(key, []).append(rec)
        self._file = open(path, "a")

    def _persist(self, kind: str, record: Persistable) -> None:
        if self._file is None:
            return
        self._file.write(json.dumps(
            {"kind": kind, "record": dataclasses.asdict(record)}) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None
