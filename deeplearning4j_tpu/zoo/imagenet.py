"""ImageNet class labels + prediction decoding.

Reference parity: `zoo/util/imagenet/ImageNetLabels.java` — loads the
1000-class index JSON (the reference fetches
`http://blob.deeplearning4j.org/utils/imagenet_class_index.json`, the
same `{"0": ["n01440764", "tench"], ...}` file Keras publishes) and
renders top-k prediction strings (`decodePredictions`).

Zero-egress behavior: resolution order is explicit path → cached file →
download; if all fail, deterministic placeholder labels ("class_i") are
used and flagged via `.synthetic` — the same honest-fallback policy as
`data/datasets.py`.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

JSON_URL = "http://blob.deeplearning4j.org/utils/imagenet_class_index.json"
_FILENAME = "imagenet_class_index.json"


class ImageNetLabels:
    """Reference: `ImageNetLabels.java` (getLabel / decodePredictions)."""

    def __init__(self, path: Optional[str] = None, *,
                 allow_download: bool = True):
        self.synthetic = False
        data = self._load(path, allow_download)
        if data is None:
            self.synthetic = True
            self._wnids = [f"n{i:08d}" for i in range(1000)]
            self._labels = [f"class_{i}" for i in range(1000)]
        else:
            n = len(data)
            self._wnids = [data[str(i)][0] for i in range(n)]
            self._labels = [data[str(i)][1] for i in range(n)]

    def _load(self, path, allow_download):
        from deeplearning4j_tpu.zoo.pretrained import cache_dir

        candidates = []
        if path:
            candidates.append(path)
        cached = os.path.join(cache_dir(), _FILENAME)
        candidates.append(cached)
        for p in candidates:
            if os.path.exists(p):
                with open(p) as f:
                    return json.load(f)
        if allow_download:
            try:
                import urllib.request

                urllib.request.urlretrieve(JSON_URL, cached)  # nosec
                with open(cached) as f:
                    return json.load(f)
            except Exception:
                if os.path.exists(cached):
                    os.remove(cached)
        return None

    def __len__(self) -> int:
        return len(self._labels)

    def get_label(self, idx: int) -> str:
        """Reference: `ImageNetLabels.getLabel(int)`."""
        return self._labels[idx]

    def wnid(self, idx: int) -> str:
        return self._wnids[idx]

    def decode_predictions(self, predictions, top: int = 5
                           ) -> List[List[Tuple[str, str, float]]]:
        """[batch, 1000] probabilities → per-example top-k
        (wnid, label, probability). Reference:
        `ImageNetLabels.decodePredictions(INDArray)`."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None]
        out = []
        for row in p:
            order = np.argsort(-row)[:top]
            out.append([(self._wnids[i], self._labels[i], float(row[i]))
                        for i in order])
        return out
