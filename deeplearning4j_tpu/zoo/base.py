"""ZooModel base.

Reference parity: `zoo/ZooModel.java` — `init()` builds the network,
`initPretrained()` loads cached weights (`:40-52`).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple, Type

ZOO_REGISTRY: Dict[str, Type] = {}


def register_zoo(cls):
    ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ZooModel:
    """Base: subclasses define conf()/init()."""

    name: str = "zoomodel"
    num_classes: int = 1000
    input_shape: Tuple[int, ...] = (224, 224, 3)

    def __init__(self, num_classes: Optional[int] = None,
                 input_shape: Optional[Tuple[int, ...]] = None,
                 seed: int = 123, **kw):
        if num_classes is not None:
            self.num_classes = num_classes
        if input_shape is not None:
            self.input_shape = tuple(input_shape)
        self.seed = seed
        self.kw = kw

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network. Reference: `ZooModel.init()`."""
        conf = self.conf()
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        if isinstance(conf, MultiLayerConfiguration):
            from deeplearning4j_tpu.models import MultiLayerNetwork
            return MultiLayerNetwork(conf).init()
        from deeplearning4j_tpu.models import ComputationGraph
        return ComputationGraph(conf).init()

    def pretrained_path(self) -> str:
        from deeplearning4j_tpu.data.datasets import data_dir
        return os.path.join(data_dir(), "zoo",
                            f"{type(self).__name__.lower()}.zip")

    def init_pretrained(self):
        """Reference: `ZooModel.initPretrained()` — cache-dir load (no
        egress in this environment; no silent download)."""
        p = self.pretrained_path()
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"No pretrained weights at {p}; place a checkpoint zip there "
                f"(this environment cannot download)")
        from deeplearning4j_tpu.models.serialize import load_model
        return load_model(p)
