"""ZooModel base.

Reference parity: `zoo/ZooModel.java` — `init()` builds the network,
`initPretrained()` loads cached weights (`:40-52`).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple, Type

ZOO_REGISTRY: Dict[str, Type] = {}


def register_zoo(cls):
    ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ZooModel:
    """Base: subclasses define conf()/init()."""

    name: str = "zoomodel"
    num_classes: int = 1000
    input_shape: Tuple[int, ...] = (224, 224, 3)

    def __init__(self, num_classes: Optional[int] = None,
                 input_shape: Optional[Tuple[int, ...]] = None,
                 seed: int = 123, **kw):
        if num_classes is not None:
            self.num_classes = num_classes
        if input_shape is not None:
            self.input_shape = tuple(input_shape)
        self.seed = seed
        self.kw = kw

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network. Reference: `ZooModel.init()`."""
        conf = self.conf()
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        if isinstance(conf, MultiLayerConfiguration):
            from deeplearning4j_tpu.models import MultiLayerNetwork
            return MultiLayerNetwork(conf).init()
        from deeplearning4j_tpu.models import ComputationGraph
        return ComputationGraph(conf).init()

    def pretrained_path(self, kind: str = "imagenet") -> str:
        """Kind-specific cache location (a kind-less name would let a
        cached imagenet file satisfy a cifar10 request)."""
        from deeplearning4j_tpu.data.datasets import data_dir
        return os.path.join(data_dir(), "zoo",
                            f"{type(self).__name__.lower()}_{kind}.zip")

    def pretrained_available(self, kind: str = "imagenet") -> bool:
        """Reference: `ZooModel.pretrainedAvailable`."""
        from deeplearning4j_tpu.zoo.pretrained import PRETRAINED_CATALOG

        return (type(self).__name__, kind) in PRETRAINED_CATALOG

    def init_pretrained(self, kind: str = "imagenet", *,
                        path: Optional[str] = None):
        """Reference: `ZooModel.initPretrained():40-75` — resolve weights
        (explicit path → model-named cache file → catalog fetch with
        Adler32 verification) and load any supported format (native zip,
        DL4J zip via interop, Keras .h5)."""
        from deeplearning4j_tpu.zoo.pretrained import (
            fetch_pretrained, load_pretrained,
        )

        if path is None:
            local = self.pretrained_path(kind)
            if os.path.exists(local):
                path = local
            else:
                path = fetch_pretrained(type(self).__name__, kind)
        return load_pretrained(path)
