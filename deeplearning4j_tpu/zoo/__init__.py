"""Model zoo.

Reference parity: `deeplearning4j-zoo` (`zoo/ZooModel.java:40-52`,
`ModelSelector.java`) — catalog: LeNet, AlexNet, VGG16/19, GoogLeNet,
ResNet50, InceptionResNetV1, FaceNetNN4Small2, SimpleCNN,
TextGenerationLSTM. All NHWC / TPU-layout; conv stacks compile onto the MXU
with no helper seam.

`init_pretrained()` mirrors `ZooModel.initPretrained()`: loads weights from
the local cache dir (`~/.deeplearning4j_tpu/zoo/<name>.zip`); this
environment has no egress, so absent files raise with the expected path
instead of downloading.
"""

from deeplearning4j_tpu.zoo.base import ZooModel, ZOO_REGISTRY
from deeplearning4j_tpu.zoo.models import (
    LeNet, AlexNet, SimpleCNN, VGG16, VGG19, TextGenerationLSTM,
)
from deeplearning4j_tpu.zoo.resnet import ResNet50
from deeplearning4j_tpu.zoo.inception import (
    GoogLeNet, InceptionResNetV1, FaceNetNN4Small2,
)
from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer
from deeplearning4j_tpu.zoo.pretrained import (
    PRETRAINED_CATALOG, PretrainedType, fetch_pretrained, load_pretrained,
    sniff_format,
)
from deeplearning4j_tpu.zoo.imagenet import ImageNetLabels

__all__ = [
    "PRETRAINED_CATALOG", "PretrainedType", "fetch_pretrained",
    "load_pretrained", "sniff_format", "ImageNetLabels",
    "ZooModel", "ZOO_REGISTRY", "LeNet", "AlexNet", "SimpleCNN", "VGG16",
    "VGG19", "TextGenerationLSTM", "ResNet50", "GoogLeNet",
    "InceptionResNetV1", "FaceNetNN4Small2", "TextGenerationTransformer",
]
