"""Sequential zoo models: LeNet, AlexNet, SimpleCNN, VGG16/19,
TextGenerationLSTM.

Reference parity: `zoo/model/{LeNet,AlexNet,SimpleCNN,VGG16,VGG19,
TextGenerationLSTM}.java`. Architectures mirror the reference configs
(kernels/strides/widths), expressed in NHWC with bf16-friendly widths.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, DropoutLayer,
    LocalResponseNormalization, LSTM, OutputLayer, RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.optim.updaters import Adam, Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_zoo


@register_zoo
class LeNet(ZooModel):
    """Reference: `zoo/model/LeNet.java` (conv5x5x20 → pool → conv5x5x50 →
    pool → dense500 → softmax) — BASELINE config #1."""

    num_classes = 10
    input_shape = (28, 28, 1)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Adam(1e-3)))
                .weight_init("xavier")
                .activation("identity")
                .list(
                    ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1),
                                     activation="identity"),
                    SubsamplingLayer(pooling="max", kernel=(2, 2), stride=(2, 2)),
                    ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1),
                                     activation="identity"),
                    SubsamplingLayer(pooling="max", kernel=(2, 2), stride=(2, 2)),
                    DenseLayer(n_out=500, activation="relu"),
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"))
                .set_input_type(InputType.convolutional_flat(h, w, c))
                .build())


@register_zoo
class AlexNet(ZooModel):
    """Reference: `zoo/model/AlexNet.java` (5 conv + LRN + 3 dense)."""

    num_classes = 1000
    input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Nesterovs(1e-2, 0.9)))
                .weight_init("normal")
                .activation("relu")
                .list(
                    ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4)),
                    LocalResponseNormalization(),
                    SubsamplingLayer(pooling="max", kernel=(3, 3), stride=(2, 2)),
                    ConvolutionLayer(n_out=256, kernel=(5, 5), stride=(1, 1),
                                     padding=(2, 2)),
                    LocalResponseNormalization(),
                    SubsamplingLayer(pooling="max", kernel=(3, 3), stride=(2, 2)),
                    ConvolutionLayer(n_out=384, kernel=(3, 3), padding=(1, 1)),
                    ConvolutionLayer(n_out=384, kernel=(3, 3), padding=(1, 1)),
                    ConvolutionLayer(n_out=256, kernel=(3, 3), padding=(1, 1)),
                    SubsamplingLayer(pooling="max", kernel=(3, 3), stride=(2, 2)),
                    DenseLayer(n_out=4096, dropout=0.5),
                    DenseLayer(n_out=4096, dropout=0.5),
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


@register_zoo
class SimpleCNN(ZooModel):
    """Reference: `zoo/model/SimpleCNN.java`."""

    num_classes = 10
    input_shape = (48, 48, 3)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Adam(1e-3)))
                .activation("relu")
                .list(
                    ConvolutionLayer(n_out=16, kernel=(3, 3), padding=(1, 1)),
                    BatchNormalization(),
                    ConvolutionLayer(n_out=16, kernel=(3, 3), padding=(1, 1)),
                    BatchNormalization(),
                    SubsamplingLayer(pooling="max", kernel=(2, 2), stride=(2, 2)),
                    ConvolutionLayer(n_out=32, kernel=(3, 3), padding=(1, 1)),
                    BatchNormalization(),
                    ConvolutionLayer(n_out=32, kernel=(3, 3), padding=(1, 1)),
                    BatchNormalization(),
                    SubsamplingLayer(pooling="max", kernel=(2, 2), stride=(2, 2)),
                    DenseLayer(n_out=256, dropout=0.5),
                    OutputLayer(n_out=self.num_classes, activation="softmax"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class _VGG(ZooModel):
    blocks = ()

    def conf(self):
        h, w, c = self.input_shape
        layers = []
        for widths in self.blocks:
            for n in widths:
                layers.append(ConvolutionLayer(
                    n_out=n, kernel=(3, 3), padding=(1, 1), activation="relu"))
            layers.append(SubsamplingLayer(
                pooling="max", kernel=(2, 2), stride=(2, 2)))
        layers += [
            DenseLayer(n_out=4096, activation="relu", dropout=0.5),
            DenseLayer(n_out=4096, activation="relu", dropout=0.5),
            OutputLayer(n_out=self.num_classes, activation="softmax"),
        ]
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Nesterovs(1e-2, 0.9)))
                .weight_init("xavier")
                .list(*layers)
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


@register_zoo
class VGG16(_VGG):
    """Reference: `zoo/model/VGG16.java` — BASELINE config #2."""

    num_classes = 1000
    input_shape = (224, 224, 3)
    blocks = ((64, 64), (128, 128), (256, 256, 256),
              (512, 512, 512), (512, 512, 512))


@register_zoo
class VGG19(_VGG):
    """Reference: `zoo/model/VGG19.java`."""

    num_classes = 1000
    input_shape = (224, 224, 3)
    blocks = ((64, 64), (128, 128), (256, 256, 256, 256),
              (512, 512, 512, 512), (512, 512, 512, 512))


@register_zoo
class TextGenerationLSTM(ZooModel):
    """Reference: `zoo/model/TextGenerationLSTM.java` — 2×LSTM(256) +
    per-timestep softmax for character-level generation."""

    num_classes = 77          # totalUniqueCharacters in the reference
    input_shape = (40, 77)    # (timesteps, vocab)

    def conf(self):
        t, vocab = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Adam(1e-3)))
                .activation("tanh")
                .list(
                    LSTM(n_out=256),
                    LSTM(n_out=256),
                    RnnOutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(vocab, t))
                .tbptt(50)
                .build())
