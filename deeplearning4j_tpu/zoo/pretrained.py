"""Pretrained-weight catalog + fetch/verify/load machinery for the zoo.

Reference parity: `zoo/ZooModel.java:28-75` — `initPretrained(type)`
resolves a per-model URL (`pretrainedUrl`), downloads to
`~/.deeplearning4j/`, verifies an Adler32 checksum
(`pretrainedChecksum`), and restores via ModelSerializer. The catalog
below carries the reference's own published URLs and Adler32 checksums
verbatim, so a file fetched for DL4J validates identically here.

Loading understands three formats (sniffed from the file):
- this framework's native checkpoint zip (models/serialize.py),
- the reference's DL4J zip container (interop/dl4j.py — the
  `configuration.json` + `coefficients.bin` layout the published zoo
  files use),
- Keras .h5 (keras_import/) for weights converted via Keras.

Zero-egress environments: the download step raises with the exact URL +
cache path so the file can be fetched out-of-band and dropped in place —
never a silent failure.
"""

from __future__ import annotations

import dataclasses
import os
import zipfile
import zlib
from typing import Dict, Optional, Tuple


class PretrainedType:
    """Reference: `zoo/PretrainedType.java` enum."""

    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


@dataclasses.dataclass(frozen=True)
class PretrainedEntry:
    url: str
    adler32: int       # 0 = unverified (reference convention)


# (model class name, pretrained type) → entry. URLs + Adler32 checksums are
# the reference's published values (VGG16.java:58-78, VGG19.java:56-68,
# ResNet50.java:56-68, LeNet.java:54-66, GoogLeNet.java:58-70).
PRETRAINED_CATALOG: Dict[Tuple[str, str], PretrainedEntry] = {
    ("VGG16", PretrainedType.IMAGENET): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/vgg16_dl4j_inference.zip",
        3501732770),
    ("VGG16", PretrainedType.CIFAR10): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/"
        "vgg16_dl4j_cifar10_inference.v1.zip", 2192260131),
    ("VGG16", PretrainedType.VGGFACE): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/"
        "vgg16_dl4j_vggface_inference.v1.zip", 2706403553),
    ("VGG19", PretrainedType.IMAGENET): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/vgg19_dl4j_inference.zip",
        2782932419),
    ("ResNet50", PretrainedType.IMAGENET): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/resnet50_dl4j_inference.zip",
        1982516793),
    ("LeNet", PretrainedType.MNIST): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/"
        "lenet_dl4j_mnist_inference.zip", 3337733202),
    # GoogLeNet.java:68 repeats LeNet's checksum verbatim — an apparent
    # copy-paste bug in the reference (two distinct zips cannot share an
    # Adler32). Kept unverified (0) so a genuine download isn't rejected.
    ("GoogLeNet", PretrainedType.IMAGENET): PretrainedEntry(
        "http://blob.deeplearning4j.org/models/googlenet_dl4j_inference.zip",
        0),
}


def cache_dir() -> str:
    from deeplearning4j_tpu.data.datasets import data_dir

    d = os.path.join(data_dir(), "zoo")
    os.makedirs(d, exist_ok=True)
    return d


def adler32_of(path: str) -> int:
    """Reference: ZooModel.initPretrained's Adler32 over the file."""
    value = 1
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            value = zlib.adler32(chunk, value)
    return value & 0xFFFFFFFF


def fetch_pretrained(model_name: str, kind: str,
                     dest: Optional[str] = None) -> str:
    """Resolve from cache or download + checksum-verify. Returns the local
    path. Reference: `ZooModel.initPretrained:40-75`."""
    entry = PRETRAINED_CATALOG.get((model_name, kind))
    if entry is None:
        raise ValueError(
            f"Pretrained {kind!r} weights are not available for "
            f"{model_name} (reference parity: pretrainedUrl returns null)")
    dest = dest or os.path.join(cache_dir(), os.path.basename(entry.url))
    if not os.path.exists(dest):
        try:
            import urllib.request

            urllib.request.urlretrieve(entry.url, dest)  # nosec - catalog URL
        except Exception as e:
            if os.path.exists(dest):
                os.remove(dest)
            raise IOError(
                f"Could not download {entry.url} ({e}). Fetch it out-of-band "
                f"and place it at {dest} — this environment may have no "
                f"egress.") from e
    if entry.adler32:
        got = adler32_of(dest)
        if got != entry.adler32:
            os.remove(dest)  # keep the cache clean so a retry re-downloads
            raise IOError(
                f"Checksum mismatch for {dest}: adler32 {got} != expected "
                f"{entry.adler32} — corrupt download removed; retry")
    return dest


def sniff_format(path: str) -> str:
    """native | dl4j | keras_h5 — decided by file contents, not extension."""
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        if "metadata.json" in names and "coefficients.npz" in names:
            return "native"
        if "configuration.json" in names and "coefficients.bin" in names:
            return "dl4j"
        raise ValueError(
            f"{path}: zip is neither a native checkpoint "
            "(metadata.json+coefficients.npz) nor a DL4J container "
            "(configuration.json+coefficients.bin)")
    with open(path, "rb") as f:
        magic = f.read(8)
    if magic.startswith(b"\x89HDF"):
        return "keras_h5"
    raise ValueError(f"{path}: unrecognized checkpoint format")


def load_pretrained(path: str):
    """Load a checkpoint of any supported format into a network."""
    fmt = sniff_format(path)
    if fmt == "native":
        from deeplearning4j_tpu.models.serialize import load_model

        return load_model(path)
    if fmt == "dl4j":
        from deeplearning4j_tpu.interop import import_dl4j_model

        return import_dl4j_model(path)
    from deeplearning4j_tpu.keras_import import import_keras_model_and_weights

    return import_keras_model_and_weights(path)
