"""ResNet-50 as a ComputationGraph — the north-star benchmark model.

Reference parity: `zoo/model/ResNet50.java:82` (`init()`), identity/conv
blocks `:91-132`, graphBuilder `:173`. Same topology (stem 7×7/2 + maxpool,
stages [3,4,6,3] of bottleneck blocks, global average pool, softmax head) in
NHWC with BN folded next to each conv — the layout XLA fuses best on TPU.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, FusedConvBNLayer,
    GlobalPoolingLayer, OutputLayer, SpaceToDepthLayer, SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.optim.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_zoo


def fold_stem_kernel(w, block: int = 2, pad: int = 3):
    """Fold a stride-`block` stem kernel [K, K, C, O] (HWIO) into the
    kernel of the mathematically IDENTICAL stride-1 conv over the
    space-to-depth input: conv(x, w, stride=2, pad=3) ==
    conv(s2d(x), fold(w), stride=1, explicit pad (2,1)).

    Derivation: index i-pad = block*a + d decomposes every original tap
    into a folded tap `a` and an input channel slot `d` — the MLPerf
    ResNet stem transform, giving the MXU block²·C input channels
    instead of C."""
    w = np.asarray(w)
    K, _, C, O = w.shape
    s = block
    taps = []
    for i in range(K):
        d = (i - pad) % s
        taps.append(((i - pad - d) // s, d))
    amin = min(a for a, _ in taps)
    amax = max(a for a, _ in taps)
    Ka = amax - amin + 1
    out = np.zeros((Ka, Ka, s * s * C, O), w.dtype)
    for i, (ai, dy) in enumerate(taps):
        for j, (aj, dx) in enumerate(taps):
            out[ai - amin, aj - amin,
                (dy * s + dx) * C:(dy * s + dx) * C + C] = w[i, j]
    return out, (-amin, Ka - 1 + amin)   # kernel + (pad_before, pad_after)


@register_zoo
class ResNet50(ZooModel):
    num_classes = 1000
    input_shape = (224, 224, 3)

    def _conv_bn(self, g, name, inp, n_out, kernel, stride=(1, 1),
                 pad=(0, 0), act="relu", mode="truncate"):
        # fused=True: the bottleneck convs run as ONE Pallas conv+BN-stats
        # kernel instead of conv->stats->normalize HBM sweeps
        # (ops/conv_fused.py; opt-in like stem="s2d" until measured).
        # Covers the 1x1s (reduce/expand/projection, ~2/3 of conv FLOPs)
        # and the 3x3 stride-1 SAME middles (the remaining third).
        from deeplearning4j_tpu.models.fusion import fusable_conv_shape

        if self.kw.get("fused") and fusable_conv_shape(kernel, stride,
                                                       pad, mode):
            g.add_layer(f"{name}_convbn",
                        FusedConvBNLayer(n_out=n_out, kernel=kernel,
                                         stride=stride, activation=act),
                        inp)
            return f"{name}_convbn"
        g.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                     padding=pad, convolution_mode=mode,
                                     activation="identity", has_bias=False),
                    inp)
        g.add_layer(f"{name}_bn", BatchNormalization(activation=act),
                    f"{name}_conv")
        return f"{name}_bn"

    def _conv_block(self, g, name, inp, filters, stride):
        """Reference: ResNet50.java convBlock `:112-132` (projection
        shortcut)."""
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", inp, f1, (1, 1), stride)
        x = self._conv_bn(g, f"{name}_b", x, f2, (3, 3), (1, 1), mode="same")
        x = self._conv_bn(g, f"{name}_c", x, f3, (1, 1), act="identity")
        sc = self._conv_bn(g, f"{name}_sc", inp, f3, (1, 1), stride,
                           act="identity")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def _identity_block(self, g, name, inp, filters):
        """Reference: ResNet50.java identityBlock `:91-110`."""
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", inp, f1, (1, 1))
        x = self._conv_bn(g, f"{name}_b", x, f2, (3, 3), (1, 1), mode="same")
        x = self._conv_bn(g, f"{name}_c", x, f3, (1, 1), act="identity")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, inp)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.kw.get("updater", Nesterovs(1e-1, 0.9)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        # Stem (reference: graphBuilder `:173` stem section).
        # stem="s2d": space-to-depth variant — identical math (see
        # fold_stem_kernel), but the conv reads 12 input channels instead
        # of 3, quadrupling MXU input-channel utilization (MLPerf ResNet
        # optimization; opt-in, default stem matches the reference).
        if self.kw.get("stem") == "s2d":
            g.add_layer("s2d", SpaceToDepthLayer(block=2), "input")
            g.add_layer("pad0", ZeroPaddingLayer(pad=((2, 1), (2, 1))),
                        "s2d")
            x = self._conv_bn(g, "stem", "pad0", 64, (4, 4), (1, 1))
        else:
            g.add_layer("pad0", ZeroPaddingLayer(pad=(3, 3)), "input")
            x = self._conv_bn(g, "stem", "pad0", 64, (7, 7), (2, 2))
        g.add_layer("pool0",
                    SubsamplingLayer(pooling="max", kernel=(3, 3),
                                     stride=(2, 2), convolution_mode="same"),
                    x)
        x = "pool0"

        stages = [
            ("res2", (64, 64, 256), 3, (1, 1)),
            ("res3", (128, 128, 512), 4, (2, 2)),
            ("res4", (256, 256, 1024), 6, (2, 2)),
            ("res5", (512, 512, 2048), 3, (2, 2)),
        ]
        for sname, filters, blocks, stride in stages:
            x = self._conv_block(g, f"{sname}a", x, filters, stride)
            for b in range(1, blocks):
                x = self._identity_block(g, f"{sname}{chr(97 + b)}", x, filters)

        g.add_layer("avgpool", GlobalPoolingLayer(pooling="avg"), x)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"),
                    "avgpool")
        g.set_outputs("output")
        return g.build()
