"""Inception-family zoo models: GoogLeNet, InceptionResNetV1,
FaceNetNN4Small2.

Reference parity: `zoo/model/{GoogLeNet,InceptionResNetV1,
FaceNetNN4Small2}.java`. GoogLeNet mirrors the 9-module Szegedy topology;
InceptionResNetV1 keeps the reference's stem/A/B/C residual-block structure
(block counts 5/10/5); FaceNetNN4Small2 is the inception-based embedding
net with an L2-normalized bottleneck and center-loss training head
(reference uses CenterLossOutputLayer the same way).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import (
    ElementWiseVertex, L2NormalizeVertex, MergeVertex, ScaleVertex,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, GlobalPoolingLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.special import CenterLossOutputLayer
from deeplearning4j_tpu.optim.updaters import Adam, Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_zoo


def _conv(g, name, inp, n_out, kernel=(1, 1), stride=(1, 1), mode="same",
          act="relu", bn=True):
    g.add_layer(f"{name}_c",
                ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                 convolution_mode=mode, activation="identity",
                                 has_bias=not bn),
                inp)
    if bn:
        g.add_layer(f"{name}_bn", BatchNormalization(activation=act),
                    f"{name}_c")
        return f"{name}_bn"
    g.add_layer(f"{name}_a", ActivationLayer(activation=act), f"{name}_c")
    return f"{name}_a"


@register_zoo
class GoogLeNet(ZooModel):
    num_classes = 1000
    input_shape = (224, 224, 3)

    def _inception(self, g, name, inp, b1, b3r, b3, b5r, b5, pp):
        a = _conv(g, f"{name}_1x1", inp, b1)
        b = _conv(g, f"{name}_3x3r", inp, b3r)
        b = _conv(g, f"{name}_3x3", b, b3, (3, 3))
        c = _conv(g, f"{name}_5x5r", inp, b5r)
        c = _conv(g, f"{name}_5x5", c, b5, (5, 5))
        g.add_layer(f"{name}_pool",
                    SubsamplingLayer(pooling="max", kernel=(3, 3),
                                     stride=(1, 1), convolution_mode="same"),
                    inp)
        d = _conv(g, f"{name}_poolproj", f"{name}_pool", pp)
        g.add_vertex(f"{name}", MergeVertex(), a, b, c, d)
        return name

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.kw.get("updater", Nesterovs(1e-2, 0.9)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = _conv(g, "stem1", "input", 64, (7, 7), (2, 2))
        g.add_layer("pool1", SubsamplingLayer(pooling="max", kernel=(3, 3),
                                              stride=(2, 2),
                                              convolution_mode="same"), x)
        x = _conv(g, "stem2", "pool1", 64)
        x = _conv(g, "stem3", x, 192, (3, 3))
        g.add_layer("pool2", SubsamplingLayer(pooling="max", kernel=(3, 3),
                                              stride=(2, 2),
                                              convolution_mode="same"), x)
        x = self._inception(g, "i3a", "pool2", 64, 96, 128, 16, 32, 32)
        x = self._inception(g, "i3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("pool3", SubsamplingLayer(pooling="max", kernel=(3, 3),
                                              stride=(2, 2),
                                              convolution_mode="same"), x)
        x = self._inception(g, "i4a", "pool3", 192, 96, 208, 16, 48, 64)
        x = self._inception(g, "i4b", x, 160, 112, 224, 24, 64, 64)
        x = self._inception(g, "i4c", x, 128, 128, 256, 24, 64, 64)
        x = self._inception(g, "i4d", x, 112, 144, 288, 32, 64, 64)
        x = self._inception(g, "i4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("pool4", SubsamplingLayer(pooling="max", kernel=(3, 3),
                                              stride=(2, 2),
                                              convolution_mode="same"), x)
        x = self._inception(g, "i5a", "pool4", 256, 160, 320, 32, 128, 128)
        x = self._inception(g, "i5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation="softmax"), "dropout")
        g.set_outputs("output")
        return g.build()


class _InceptionResNetBase(ZooModel):
    """Shared stem + residual A/B/C block machinery."""

    def _stem(self, g):
        x = _conv(g, "stem1", "input", 32, (3, 3), (2, 2), mode="truncate")
        x = _conv(g, "stem2", x, 32, (3, 3), mode="truncate")
        x = _conv(g, "stem3", x, 64, (3, 3))
        g.add_layer("stem_pool",
                    SubsamplingLayer(pooling="max", kernel=(3, 3),
                                     stride=(2, 2), convolution_mode="same"),
                    x)
        x = _conv(g, "stem4", "stem_pool", 80)
        x = _conv(g, "stem5", x, 192, (3, 3), mode="truncate")
        x = _conv(g, "stem6", x, 256, (3, 3), (2, 2))
        return x

    def _block_a(self, g, name, inp, scale=0.17):
        """Inception-ResNet-A (35×35) — residual scaling as in the
        reference (`ScaleVertex`)."""
        a = _conv(g, f"{name}_b1", inp, 32)
        b = _conv(g, f"{name}_b2a", inp, 32)
        b = _conv(g, f"{name}_b2b", b, 32, (3, 3))
        c = _conv(g, f"{name}_b3a", inp, 32)
        c = _conv(g, f"{name}_b3b", c, 32, (3, 3))
        c = _conv(g, f"{name}_b3c", c, 32, (3, 3))
        g.add_vertex(f"{name}_cat", MergeVertex(), a, b, c)
        lin = _conv(g, f"{name}_lin", f"{name}_cat", 256, act="identity",
                    bn=False)
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), lin)
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return name

    def _reduction_a(self, g, name, inp):
        a = _conv(g, f"{name}_b1", inp, 384, (3, 3), (2, 2))
        b = _conv(g, f"{name}_b2a", inp, 192)
        b = _conv(g, f"{name}_b2b", b, 192, (3, 3))
        b = _conv(g, f"{name}_b2c", b, 256, (3, 3), (2, 2))
        g.add_layer(f"{name}_pool",
                    SubsamplingLayer(pooling="max", kernel=(3, 3),
                                     stride=(2, 2), convolution_mode="same"),
                    inp)
        g.add_vertex(name, MergeVertex(), a, b, f"{name}_pool")
        return name

    def _block_b(self, g, name, inp, channels, scale=0.10):
        a = _conv(g, f"{name}_b1", inp, 128)
        b = _conv(g, f"{name}_b2a", inp, 128)
        b = _conv(g, f"{name}_b2b", b, 128, (1, 7))
        b = _conv(g, f"{name}_b2c", b, 128, (7, 1))
        g.add_vertex(f"{name}_cat", MergeVertex(), a, b)
        lin = _conv(g, f"{name}_lin", f"{name}_cat", channels, act="identity",
                    bn=False)
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), lin)
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return name


@register_zoo
class InceptionResNetV1(ZooModel):
    num_classes = 1000
    input_shape = (160, 160, 3)
    blocks_a = 5
    blocks_b = 10

    def conf(self):
        h, w, c = self.input_shape
        base = _InceptionResNetBase(num_classes=self.num_classes,
                                    input_shape=self.input_shape,
                                    seed=self.seed)
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.kw.get("updater", Adam(1e-3)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = base._stem(g)
        for i in range(self.blocks_a):
            x = base._block_a(g, f"a{i}", x)
        x = base._reduction_a(g, "reda", x)
        for i in range(self.blocks_b):
            x = base._block_b(g, f"b{i}", x, channels=896)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling="avg"), x)
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation="softmax"), "avgpool")
        g.set_outputs("output")
        return g.build()


@register_zoo
class FaceNetNN4Small2(ZooModel):
    """Embedding net: inception trunk → 128-d L2-normalized embedding →
    center-loss softmax head (reference: FaceNetNN4Small2.java +
    CenterLossOutputLayer)."""

    num_classes = 5749  # LFW identities, reference default ballpark
    input_shape = (96, 96, 3)
    embedding_size = 128

    def conf(self):
        h, w, c = self.input_shape
        base = _InceptionResNetBase(seed=self.seed)
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.kw.get("updater", Adam(1e-3)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = base._stem(g)
        for i in range(3):
            x = base._block_a(g, f"a{i}", x)
        x = base._reduction_a(g, "reda", x)
        for i in range(2):
            x = base._block_b(g, f"b{i}", x, channels=896)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling="avg"), x)
        g.add_layer("bottleneck",
                    DenseLayer(n_out=self.embedding_size,
                               activation="identity"),
                    "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("lossLayer",
                    CenterLossOutputLayer(n_out=self.num_classes,
                                          activation="softmax",
                                          alpha=0.9, lambda_=1e-4),
                    "embeddings")
        g.set_outputs("lossLayer")
        return g.build()
