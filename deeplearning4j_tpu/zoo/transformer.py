"""Transformer zoo models — modern extension beyond the reference zoo.

The reference zoo's sequence model is TextGenerationLSTM
(`zoo/model/TextGenerationLSTM.java`); these are its transformer-class
successors, required by the project charter's long-context mandate
(SURVEY §7 step 7). Built entirely from the framework's own layers:
EmbeddingSequenceLayer + PositionEmbeddingLayer + TransformerEncoderBlock
(flash attention on TPU inference; MoE experts optional; ring attention
under a `seq`-axis mesh via parallel.ring_attention).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.attention import (
    PositionEmbeddingLayer, TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingSequenceLayer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel, register_zoo


@register_zoo
class TextGenerationTransformer(ZooModel):
    """GPT-style causal byte/char LM.

    Inputs: token ids as [batch, time, 1]; outputs per-timestep softmax
    over the vocabulary (same contract as TextGenerationLSTM, so the
    text-generation tooling is interchangeable).
    """

    num_classes = 256             # byte vocabulary
    input_shape = (256, 1)        # (timesteps, 1 token-id channel)

    def __init__(self, *args, d_model: int = 256, num_heads: int = 8,
                 num_kv_heads=None, num_blocks: int = 4, n_experts: int = 0,
                 pos_encoding: str = "learned", max_decode: int = 0,
                 norm: str = "layer", ffn_activation: str = "gelu",
                 window=None, rolling_cache: bool = False, **kw):
        super().__init__(*args, **kw)
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads   # < num_heads -> GQA
        self.num_blocks = num_blocks
        self.n_experts = n_experts
        # norm="rms" + ffn_activation="swiglu" + pos_encoding="rope" +
        # num_kv_heads < num_heads = the Llama-architecture block shape
        self.norm = norm
        self.ffn_activation = ffn_activation
        # window: int applies to every block; a list/tuple gives each
        # block its own (None = full attention) — the alternating
        # local/global pattern (Gemma-style) is window=[w, None]*k
        self.window = window
        if isinstance(window, (list, tuple)):
            if len(window) != num_blocks:
                raise ValueError(
                    f"per-block window list has {len(window)} entries "
                    f"for {num_blocks} blocks")
            if rolling_cache and any(w is None for w in window):
                raise ValueError(
                    "rolling_cache needs a window on EVERY block (a "
                    "full-attention block's cache cannot roll)")
        if rolling_cache and (window is None or pos_encoding != "rope"):
            raise ValueError(
                "rolling_cache streams unbounded generation in O(window) "
                "memory: it needs window=w and pos_encoding='rope' "
                "(learned positions cap decode length anyway)")
        if rolling_cache and max_decode:
            raise ValueError(
                "rolling_cache makes generation length unbounded — "
                "max_decode would be silently ignored; drop one of them")
        self.rolling_cache = rolling_cache
        if pos_encoding not in ("learned", "rope"):
            raise ValueError(f"pos_encoding must be 'learned' or 'rope', "
                             f"got {pos_encoding!r}")
        if max_decode and pos_encoding != "rope":
            raise ValueError(
                "max_decode extends generation past the training length, "
                "which needs pos_encoding='rope' (learned positions are "
                "hard-capped at the table size)")
        self.pos_encoding = pos_encoding
        self.max_decode = max_decode   # rope only: decode budget beyond t

    def conf(self):
        t = self.input_shape[0]
        vocab = self.num_classes
        rope = self.pos_encoding == "rope"
        # learned positions cap decode length at t, so a bigger KV cache
        # would be unreachable; RoPE has no absolute-position table, so
        # the cache (and thus generation) may extend past the training t.
        # A rolling cache needs only prefill + window slots — generation
        # length is unbounded in that fixed buffer.
        per_block = (list(self.window)
                     if isinstance(self.window, (list, tuple))
                     else [self.window] * self.num_blocks)

        def block_cache(w):
            if self.rolling_cache:
                return t + w - 1     # prefill + window ring slots
            return max(t, self.max_decode) if rope else t

        blocks = [
            TransformerEncoderBlock(
                num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
                causal=True, n_experts=self.n_experts,
                max_cache=block_cache(w), rope=rope, norm=self.norm,
                ffn_activation=self.ffn_activation, window=w,
                rolling_cache=self.rolling_cache)
            for w in per_block
        ]
        pos = [] if rope else [PositionEmbeddingLayer(max_length=t)]
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.kw.get("updater", Adam(3e-4)))
                .activation("identity")
                .weight_init("xavier")
                .list(
                    EmbeddingSequenceLayer(n_in=vocab, n_out=self.d_model,
                                           activation="identity"),
                    *pos,
                    *blocks,
                    RnnOutputLayer(n_out=vocab, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.recurrent(1, t))
                .build())
