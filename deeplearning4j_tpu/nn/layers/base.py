"""Layer base class + registry.

Reference parity: `nn/api/Layer.java:70-310` (activate / backpropGradient /
preOutput) and `nn/conf/layers/Layer.java` (config base with cascaded
activation/weightInit/updater/l1/l2/dropout — see
`NeuralNetConfiguration.Builder`, reference `nn/conf/NeuralNetConfiguration.java:515`).

Differences by design (TPU-first):
- No `backpropGradient`: gradients come from `jax.grad` of the whole network.
- No mutable layer objects: `apply` is pure; BN running stats etc. live in an
  explicit `state` pytree returned alongside activations.
- `dropout` here is the DROP probability (modern convention), not the
  reference's retain probability; inverted dropout scaling matches either way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.initializers import WeightInit
from deeplearning4j_tpu.nn.inputs import InputType

LAYER_REGISTRY: Dict[str, type] = {}

Params = Dict[str, jax.Array]
State = Dict[str, jax.Array]


def register_layer(cls):
    """Register a layer class for config serde + custom-layer plug-ins
    (reference seam: custom layer tests `nn/layers/custom/`)."""
    LAYER_REGISTRY[cls.__name__] = cls
    from deeplearning4j_tpu.utils.serde import register_serde

    return register_serde(cls)


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base layer config/impl. All fields optional → cascaded from the global
    builder defaults at build() time (reference: config cloning in
    `MultiLayerConfiguration.Builder`)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    updater: Optional[Any] = None          # per-layer updater override
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None        # drop probability (see module doc)
    learning_rate: Optional[Any] = None    # per-layer LR override
    bias_init: Optional[float] = None
    frozen: bool = False                   # transfer-learning freeze flag

    # ---- wiring API ----
    def with_defaults(self, **defaults) -> "Layer":
        """Fill None fields from global defaults (config cascade)."""
        updates = {
            k: v for k, v in defaults.items()
            if v is not None
            and k in {f.name for f in dataclasses.fields(self)}
            and getattr(self, k) is None
        }
        return dataclasses.replace(self, **updates) if updates else self

    def infer_n_in(self, input_type: InputType) -> "Layer":
        """Set n_in-like fields from the incoming InputType (reference:
        `setInputType`/`getPreProcessorForInputType` auto-wiring)."""
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # ---- runtime API (pure) ----
    def init_params(self, key, input_type: InputType, dtype=jnp.float32
                    ) -> Tuple[Params, State]:
        return {}, {}

    def apply(self, params: Params, x, *, state: Optional[State] = None,
              train: bool = False, rng=None, mask=None) -> Tuple[Any, State]:
        raise NotImplementedError

    # ---- shared helpers ----
    def _act(self, x):
        return Activation.get(self.activation)(x)

    def _winit(self):
        return WeightInit.get(self.weight_init)

    def _maybe_dropout(self, x, train: bool, rng):
        """Inverted dropout on the INPUT activations (reference:
        `BaseLayer.java:535` applyDropOutIfNecessary before preOutput)."""
        p = self.dropout
        if not train or not p or rng is None:
            return x
        keep = 1.0 - p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def regularization(self, params: Params) -> jax.Array:
        """L1/L2 penalty contribution (reference: `calcL1()`/`calcL2()` summed
        into score in computeGradientAndScore). Bias params get the separate
        l1_bias/l2_bias coefficients, like the reference."""
        total = jnp.asarray(0.0, jnp.float32)
        for k, v in params.items():
            is_bias = k in ("b", "beta", "bias")
            l1 = (self.l1_bias if is_bias else self.l1) or 0.0
            l2 = (self.l2_bias if is_bias else self.l2) or 0.0
            if l1:
                total = total + l1 * jnp.sum(jnp.abs(v))
            if l2:
                total = total + 0.5 * l2 * jnp.sum(jnp.square(v))
        return total

    @property
    def is_output_layer(self) -> bool:
        return False

    @property
    def is_pretrainable(self) -> bool:
        """Layerwise-pretrainable (reference: AutoEncoder/RBM/VAE pretrain)."""
        return False
