"""Normalization layers: BatchNorm, LRN, LayerNorm.

Reference parity: `nn/conf/layers/BatchNormalization.java` + impl
`nn/layers/normalization/BatchNormalization.java` (cuDNN helper seam at
`:56-64,125,307`) and `LocalResponseNormalization.java`. Running mean/var are
NON-trainable state carried explicitly through the train step (the reference
mutates them in place; under jit we return the new state), updated with the
reference's `decay` EMA semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(Layer):
    """Batch norm over the trailing channel/feature axis (NHWC/ BTF / BF).

    Reference: `nn/conf/layers/BatchNormalization.java` (decay `:…`, eps,
    lockGammaBeta) — gamma/beta trainable, global mean/var state."""

    n_out: Optional[int] = None   # feature count, inferred
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    scale: bool = True            # learnable gamma (Keras scale flag)
    center: bool = True           # learnable beta (Keras center flag)

    def infer_n_in(self, input_type: InputType) -> "BatchNormalization":
        if self.n_out is None:
            feat = (input_type.channels if input_type.kind in ("cnn", "cnn3d")
                    else input_type.size if input_type.kind == "rnn"
                    else input_type.flat_size())
            return dataclasses.replace(self, n_out=feat)
        return self

    def init_params(self, key, input_type, dtype=jnp.float32):
        f = self.n_out
        params = {}
        if not self.lock_gamma_beta:
            if self.scale:
                params["gamma"] = jnp.ones((f,), dtype)
            if self.center:
                params["beta"] = jnp.zeros((f,), dtype)
        state = {"mean": jnp.zeros((f,), dtype), "var": jnp.ones((f,), dtype)}
        return params, state

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            d = self.decay
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean,
                "var": d * state["var"] + (1 - d) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = 1.0 / jnp.sqrt(var + self.eps)
        y = (x - mean) * inv
        if not self.lock_gamma_beta:
            if self.scale:
                y = y * params["gamma"]
            if self.center:
                y = y + params["beta"]
        return self._act(y), new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (AlexNet-era). Reference:
    `nn/conf/layers/LocalResponseNormalization.java` + cuDNN helper
    (`CudnnLocalResponseNormalizationHelper.java`); here a slide over the
    channel axis that XLA fuses — no helper needed."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        # x: NHWC. Sum x^2 over a window of `n` adjacent channels.
        half = self.n // 2
        sq = x * x
        padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
        c = x.shape[-1]
        acc = sum(padded[..., i:i + c] for i in range(self.n))
        denom = (self.k + (self.alpha / self.n) * acc) ** self.beta
        return x / denom, state


@register_layer
@dataclasses.dataclass(frozen=True)
class LayerNormalization(Layer):
    """Layer norm over the trailing feature axis — no reference counterpart
    (DL4J 0.8 predates it); required by the modern model families this
    framework must also serve (transformers, ring attention)."""

    n_out: Optional[int] = None
    eps: float = 1e-6

    def infer_n_in(self, input_type: InputType) -> "LayerNormalization":
        if self.n_out is None:
            feat = input_type.size if input_type.kind == "rnn" else input_type.flat_size()
            return dataclasses.replace(self, n_out=feat)
        return self

    def init_params(self, key, input_type, dtype=jnp.float32):
        f = self.n_out
        return {"gamma": jnp.ones((f,), dtype), "beta": jnp.zeros((f,), dtype)}, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + self.eps)
        return self._act(y * params["gamma"] + params["beta"]), state
