"""Special layers: FrozenLayer, CenterLossOutputLayer, VAE, RBM.

Reference parity:
- `nn/layers/FrozenLayer.java` (transfer-learning freeze wrapper)
- `nn/layers/training/CenterLossOutputLayer.java`
- `nn/layers/variational/VariationalAutoencoder.java` (1,141 LoC)
- `nn/conf/layers/RBM.java` (contrastive-divergence pretraining)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
from deeplearning4j_tpu.nn.losses import LossFunction


@register_layer
@dataclasses.dataclass(frozen=True)
class FrozenLayer(Layer):
    """Wrapper marking an inner layer's params as non-trainable. The model
    masks the wrapped subtree's gradients to zero (reference:
    `nn/layers/FrozenLayer.java`, which swaps in a NoOp updater)."""

    layer: Optional[Any] = None
    frozen: bool = True

    def infer_n_in(self, input_type: InputType):
        return dataclasses.replace(self, layer=self.layer.infer_n_in(input_type))

    def with_defaults(self, **defaults):
        return dataclasses.replace(self, layer=self.layer.with_defaults(**defaults))

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.layer.init_params(key, input_type, dtype)

    def apply(self, params, x, **kw):
        # stop_gradient makes freezing robust even outside the updater mask.
        params = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.layer.apply(params, x, **kw)


@register_layer
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(OutputLayer):
    """Output layer with center loss (Wen et al.). Reference:
    `nn/layers/training/CenterLossOutputLayer.java`: per-class feature centers
    updated by EMA (alpha), center-distance penalty weighted by lambda."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_params(self, key, input_type, dtype=jnp.float32):
        params, _ = super().init_params(key, input_type, dtype)
        state = {"centers": jnp.zeros((self.n_out, self.n_in), dtype)}
        return params, state

    def score_and_state(self, params, x, labels, state, mask=None):
        base = super().score(params, x, labels, mask)
        centers = state["centers"]
        cls_centers = labels @ centers                       # [B, n_in]
        diff = x - cls_centers
        center_loss = 0.5 * jnp.mean(jnp.sum(diff * diff, axis=-1))
        # EMA center update (non-gradient state transition)
        counts = jnp.maximum(jnp.sum(labels, axis=0), 1.0)   # [n_out]
        delta = (labels.T @ diff) / counts[:, None]
        new_centers = centers + self.alpha * delta
        return base + self.lambda_ * center_loss, {"centers": new_centers}

    def score(self, params, x, labels, mask=None):
        # Stateless view (centers frozen) for eval paths.
        return super().score(params, x, labels, mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(Layer):
    """VAE as a layer, pretrainable via the ELBO; supervised forward emits the
    latent mean. Reference: `nn/layers/variational/VariationalAutoencoder.java`
    with encoder/decoder MLPs, pzx activation, reconstruction distributions
    (gaussian | bernoulli)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None            # latent size
    encoder_sizes: Sequence[int] = (64,)
    decoder_sizes: Sequence[int] = (64,)
    reconstruction_distribution: str = "gaussian"   # gaussian | bernoulli
    num_samples: int = 1

    @property
    def is_pretrainable(self) -> bool:
        return True

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _mlp_init(self, key, sizes, dtype):
        ps = []
        winit = self._winit()
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            ps.append({"W": winit(k, (a, b), dtype), "b": jnp.zeros((b,), dtype)})
        return ps, key

    def init_params(self, key, input_type, dtype=jnp.float32):
        enc_sizes = [self.n_in, *self.encoder_sizes]
        dec_sizes = [self.n_out, *self.decoder_sizes]
        enc, key = self._mlp_init(key, enc_sizes, dtype)
        dec, key = self._mlp_init(key, dec_sizes, dtype)
        key, k1, k2, k3 = jax.random.split(key, 4)
        winit = self._winit()
        eh, dh = enc_sizes[-1], dec_sizes[-1]
        rec_out = self.n_in * (2 if self.reconstruction_distribution == "gaussian" else 1)
        params = {
            "enc": {str(i): p for i, p in enumerate(enc)},
            "dec": {str(i): p for i, p in enumerate(dec)},
            "mu": {"W": winit(k1, (eh, self.n_out), dtype), "b": jnp.zeros((self.n_out,), dtype)},
            "logvar": {"W": winit(k2, (eh, self.n_out), dtype), "b": jnp.zeros((self.n_out,), dtype)},
            "rec": {"W": winit(k3, (dh, rec_out), dtype), "b": jnp.zeros((rec_out,), dtype)},
        }
        return params, {}

    def _mlp(self, blocks, x):
        act = Activation.get(self.activation or "tanh")
        for i in range(len(blocks)):
            p = blocks[str(i)]
            x = act(x @ p["W"] + p["b"])
        return x

    def encode(self, params, x):
        h = self._mlp(params["enc"], x)
        mu = h @ params["mu"]["W"] + params["mu"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mu, logvar

    def decode(self, params, z):
        h = self._mlp(params["dec"], z)
        return h @ params["rec"]["W"] + params["rec"]["b"]

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        mu, _ = self.encode(params, x)
        return mu, state

    def reconstruction_score(self, params, x, *, rng):
        """Negative ELBO (to MINIMIZE) — the pretraining objective."""
        mu, logvar = self.encode(params, x)
        total = 0.0
        for i in range(self.num_samples):
            rng, k = jax.random.split(rng)
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction_distribution == "bernoulli":
                nll = jnp.sum(
                    jax.nn.softplus(out) - x * out, axis=-1
                )  # -log p under Bernoulli(sigmoid(out))
            else:
                rmu, rlogvar = jnp.split(out, 2, axis=-1)
                nll = 0.5 * jnp.sum(
                    rlogvar + (x - rmu) ** 2 / jnp.exp(rlogvar) + jnp.log(2 * jnp.pi),
                    axis=-1,
                )
            total = total + jnp.mean(nll)
        rec = total / self.num_samples
        kl = -0.5 * jnp.mean(jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1))
        return rec + kl


@register_layer
@dataclasses.dataclass(frozen=True)
class RBM(Layer):
    """Bernoulli RBM with CD-1 pretraining. Reference: `nn/conf/layers/RBM.java`
    + `nn/layers/feedforward/rbm/`. Supervised forward = propup probabilities."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    k: int = 1   # CD-k steps

    @property
    def is_pretrainable(self) -> bool:
        return True

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {
            "W": self._winit()(key, (self.n_in, self.n_out), dtype),
            "hb": jnp.zeros((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),
        }, {}

    def propup(self, params, v):
        return jax.nn.sigmoid(v @ params["W"] + params["hb"])

    def propdown(self, params, h):
        return jax.nn.sigmoid(h @ params["W"].T + params["vb"])

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.propup(params, x), state

    def reconstruction_score(self, params, v0, *, rng):
        """CD-k free-energy difference surrogate: grad of this ≈ CD update.

        Uses the standard trick: loss = FE(v0) - FE(v_k) with v_k treated as
        constant (stop_gradient), so jax.grad reproduces contrastive
        divergence; the reference hand-codes the same update.
        """
        def free_energy(v):
            wx = v @ params["W"] + params["hb"]
            return -v @ params["vb"] - jnp.sum(jax.nn.softplus(wx), axis=-1)

        vk = v0
        for _ in range(self.k):
            rng, k1, k2 = jax.random.split(rng, 3)
            h = jax.random.bernoulli(k1, self.propup(params, vk)).astype(v0.dtype)
            vk = self.propdown(params, h)
        vk = jax.lax.stop_gradient(vk)
        return jnp.mean(free_energy(v0) - free_energy(vk))
