"""Layer configs + pure-functional implementations.

Reference parity: `nn/conf/layers/` (declarative configs) + `nn/layers/`
(imperative impls). Here config and implementation are ONE frozen dataclass:
hyperparameters are fields (JSON-serializable), behavior is pure methods
(`init_params`, `apply`, `output_type`) — so a model is data all the way down
and the whole forward pass traces into a single XLA computation.
"""

from deeplearning4j_tpu.nn.layers.base import Layer, LAYER_REGISTRY
from deeplearning4j_tpu.nn.layers.feedforward import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, AutoEncoder, PReLULayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer, Convolution1DLayer, SubsamplingLayer, Subsampling1DLayer,
    ZeroPaddingLayer, Upsampling2DLayer, SeparableConvolution2DLayer,
    Deconvolution2DLayer, DepthwiseConvolution2DLayer, Cropping2DLayer,
    FusedConvBNLayer,
    SpaceToDepthLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization, LocalResponseNormalization, LayerNormalization,
)
from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer, PoolingType
from deeplearning4j_tpu.nn.layers.recurrent import (
    LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn, GRU, RnnOutputLayer,
    Bidirectional, LastTimeStep,
)
from deeplearning4j_tpu.nn.layers.special import (
    FrozenLayer, CenterLossOutputLayer, VariationalAutoencoder, RBM,
)
from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention

__all__ = [
    "Layer", "LAYER_REGISTRY",
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer", "DropoutLayer",
    "EmbeddingLayer", "EmbeddingSequenceLayer", "AutoEncoder", "PReLULayer",
    "ConvolutionLayer", "Convolution1DLayer", "SubsamplingLayer",
    "Subsampling1DLayer", "ZeroPaddingLayer", "Upsampling2DLayer",
    "SeparableConvolution2DLayer", "Deconvolution2DLayer",
    "DepthwiseConvolution2DLayer", "Cropping2DLayer", "SpaceToDepthLayer",
    "FusedConvBNLayer",
    "BatchNormalization", "LocalResponseNormalization", "LayerNormalization",
    "GlobalPoolingLayer", "PoolingType",
    "LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "SimpleRnn", "GRU",
    "RnnOutputLayer", "Bidirectional", "LastTimeStep",
    "FrozenLayer", "CenterLossOutputLayer", "VariationalAutoencoder", "RBM",
    "MultiHeadAttention",
]
