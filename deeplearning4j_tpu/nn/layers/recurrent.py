"""Recurrent layers: LSTM family, SimpleRnn, GRU, RnnOutputLayer, wrappers.

Reference parity: `nn/layers/recurrent/GravesLSTM.java:43` +
`LSTMHelpers.java` (shared fused fwd `:62`, bwd `:291`), configs in
`nn/conf/layers/{GravesLSTM,GravesBidirectionalLSTM,LSTM,RnnOutputLayer}.java`.

TPU-first redesign:
- Activations are [batch, time, features] (the reference is [b, f, t]).
- The time loop is ONE `lax.scan`; the input projection for ALL timesteps is
  hoisted out of the scan as a single [B*T, F] @ [F, 4H] matmul on the MXU —
  only the small recurrent matmul stays sequential. This is the fusion the
  reference got from hand-written `LSTMHelpers` (and cuDNN never provided at
  this snapshot — see SURVEY §2.3 note).
- Backprop-through-time comes from `jax.grad` through the scan; truncated BPTT
  is done at the model level by slicing the sequence (reference:
  `MultiLayerNetwork.doTruncatedBPTT`).
- Stateful stepping (`rnnTimeStep`) maps to passing/returning the explicit
  carry in the `state` dict under keys "h"/"c".
- Param names follow the reference's GravesLSTMParamInitializer: "W" (input
  weights), "RW" (recurrent weights), "b".
- Per-timestep masking: when mask[t]==0 the carry is held (the reference's
  variable-length masking semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, Params, register_layer
from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
from deeplearning4j_tpu.nn.losses import LossFunction


def _mask_carry(new, old, m):
    """Hold the carry where mask==0. m: [B] for one step."""
    return jnp.where(m[:, None] > 0, new, old)


@register_layer
@dataclasses.dataclass(frozen=True)
class BaseRecurrentLayer(Layer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def initial_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError


@register_layer
@dataclasses.dataclass(frozen=True)
class LSTM(BaseRecurrentLayer):
    """Standard (peephole-free) LSTM. Reference: `nn/conf/layers/LSTM` /
    `LSTMHelpers.activateHelper` with peephole=false. Gate order i,f,g,o."""

    peephole: bool = False
    # Fused Pallas sequence kernel (ops/lstm.py — the LSTMHelpers-equivalent
    # fusion, SURVEY §7): None = auto (on TPU when gate/cell activations are
    # the standard sigmoid/tanh), True/False = force.
    fused: Optional[bool] = None

    def init_params(self, key, input_type, dtype=jnp.float32):
        h = self.n_out
        k1, k2, k3 = jax.random.split(key, 3)
        winit = self._winit()
        params = {
            "W": winit(k1, (self.n_in, 4 * h), dtype),
            "RW": winit(k2, (h, 4 * h), dtype),
            "b": jnp.zeros((4 * h,), dtype)
            .at[h:2 * h].set(self.forget_gate_bias_init),
        }
        if self.peephole:
            params["P"] = jnp.zeros((3, h), dtype)  # peep for i, f, o
        return params, {}

    def initial_carry(self, batch: int, dtype=jnp.float32):
        h = self.n_out
        return {"h": jnp.zeros((batch, h), dtype), "c": jnp.zeros((batch, h), dtype)}

    def _use_fused(self) -> bool:
        from deeplearning4j_tpu.ops.lstm import fused_lstm_available

        # NB: activation=None means IDENTITY (Activation.get(None)), not
        # tanh — the kernel hard-codes sigmoid/tanh, so require them exactly.
        ok = fused_lstm_available(self.gate_activation, self.activation)
        if self.fused is not None:
            if self.fused and not ok:
                raise ValueError(
                    f"fused=True requires gate_activation='sigmoid' and "
                    f"activation='tanh'; got {self.gate_activation!r}/"
                    f"{self.activation!r}")
            return self.fused
        from deeplearning4j_tpu.ops.kernel_defaults import lstm_policy

        return (ok and jax.default_backend() == "tpu"
                and lstm_policy() == "fused")

    def _step(self, params, carry, xw_t, m_t):
        """One scan step. xw_t: precomputed x_t @ W + b, [B, 4H]."""
        h_prev, c_prev = carry["h"], carry["c"]
        hsz = self.n_out
        gates = xw_t + h_prev @ params["RW"]
        i_, f_, g_, o_ = jnp.split(gates, 4, axis=-1)
        gate_act = Activation.get(self.gate_activation)
        if self.peephole:
            p = params["P"]
            i_ = i_ + c_prev * p[0]
            f_ = f_ + c_prev * p[1]
        i = gate_act(i_)
        f = gate_act(f_)
        g = self._act(g_)
        c = f * c_prev + i * g
        if self.peephole:
            o_ = o_ + c * params["P"][2]
        o = gate_act(o_)
        h = o * self._act(c)
        if m_t is not None:
            h = _mask_carry(h, h_prev, m_t)
            c = _mask_carry(c, c_prev, m_t)
        return {"h": h, "c": c}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._maybe_dropout(x, train, rng)
        B, T, _ = x.shape
        carry = state if state and "h" in state else self.initial_carry(B, x.dtype)
        # Hoist the big input matmul out of the scan: one [B*T,F]@[F,4H] MXU op.
        xw = x.reshape(B * T, -1) @ params["W"] + params["b"]
        xw = xw.reshape(B, T, -1).transpose(1, 0, 2)  # [T, B, 4H]
        m = None if mask is None else mask.astype(x.dtype).T  # [T, B]

        if self._use_fused():
            from deeplearning4j_tpu.ops.lstm import fused_lstm

            p = params.get("P")
            if p is None:
                p = jnp.zeros((3, self.n_out), x.dtype)
            mm = m if m is not None else jnp.ones((T, B), x.dtype)
            hs, hT, cT = fused_lstm(
                xw, params["RW"], p, carry["h"], carry["c"], mm,
                jax.default_backend() != "tpu")
            return hs.transpose(1, 0, 2), {"h": hT, "c": cT}

        def step(c, inp):
            xw_t, m_t = inp
            new = self._step(params, c, xw_t, m_t)
            return new, new["h"]

        carry, hs = lax.scan(step, carry, (xw, m) if m is not None else (xw, jnp.ones((T, B), x.dtype)))
        y = hs.transpose(1, 0, 2)  # [B, T, H]
        return y, carry


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections — the reference's workhorse RNN
    (`nn/layers/recurrent/GravesLSTM.java:43`, Graves 2013 variant)."""

    peephole: bool = True


@register_layer
@dataclasses.dataclass(frozen=True)
class GRU(BaseRecurrentLayer):
    """GRU — modern extension (the reference snapshot has no GRU impl).

    `reset_after` picks where the reset gate applies: True (default, the
    cuDNN/Keras-2 GRU-v2 variant) multiplies r into the already-computed
    recurrent matmul (n = act(xW + r·(h RW))); False (classic Cho et al. /
    Keras reset_after=False) multiplies r into the hidden state BEFORE the
    matmul (n = act(xW + (r·h) RW)). `recurrent_bias=True` adds a separate
    bias on the recurrent matmul (only meaningful with reset_after=True) —
    both are needed for exact Keras import."""

    reset_after: bool = True
    recurrent_bias: bool = False

    def init_params(self, key, input_type, dtype=jnp.float32):
        h = self.n_out
        k1, k2 = jax.random.split(key)
        winit = self._winit()
        params = {
            "W": winit(k1, (self.n_in, 3 * h), dtype),
            "RW": winit(k2, (h, 3 * h), dtype),
            "b": jnp.zeros((3 * h,), dtype),
        }
        if self.recurrent_bias:
            params["rb"] = jnp.zeros((3 * h,), dtype)
        return params, {}

    def initial_carry(self, batch: int, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._maybe_dropout(x, train, rng)
        B, T, _ = x.shape
        hsz = self.n_out
        carry = state if state and "h" in state else self.initial_carry(B, x.dtype)
        xw = (x.reshape(B * T, -1) @ params["W"] + params["b"]).reshape(B, T, -1)
        xw = xw.transpose(1, 0, 2)
        m = (mask.astype(x.dtype).T if mask is not None
             else jnp.ones((T, B), x.dtype))
        gate_act = Activation.get(self.gate_activation)

        def step(c, inp):
            xw_t, m_t = inp
            h_prev = c["h"]
            if self.reset_after:
                rh = h_prev @ params["RW"]
                if "rb" in params:
                    rh = rh + params["rb"]
                r = gate_act(xw_t[:, :hsz] + rh[:, :hsz])
                z = gate_act(xw_t[:, hsz:2 * hsz] + rh[:, hsz:2 * hsz])
                n = self._act(xw_t[:, 2 * hsz:] + r * rh[:, 2 * hsz:])
            else:
                rz = h_prev @ params["RW"][:, :2 * hsz]
                r = gate_act(xw_t[:, :hsz] + rz[:, :hsz])
                z = gate_act(xw_t[:, hsz:2 * hsz] + rz[:, hsz:])
                n = self._act(xw_t[:, 2 * hsz:]
                              + (r * h_prev) @ params["RW"][:, 2 * hsz:])
            h = (1 - z) * n + z * h_prev
            h = _mask_carry(h, h_prev, m_t)
            return {"h": h}, h

        carry, hs = lax.scan(step, carry, (xw, m))
        return hs.transpose(1, 0, 2), carry


@register_layer
@dataclasses.dataclass(frozen=True)
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h = act(x W + h_prev RW + b)."""

    def init_params(self, key, input_type, dtype=jnp.float32):
        h = self.n_out
        k1, k2 = jax.random.split(key)
        winit = self._winit()
        return {
            "W": winit(k1, (self.n_in, h), dtype),
            "RW": winit(k2, (h, h), dtype),
            "b": jnp.zeros((h,), dtype),
        }, {}

    def initial_carry(self, batch: int, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._maybe_dropout(x, train, rng)
        B, T, _ = x.shape
        carry = state if state and "h" in state else self.initial_carry(B, x.dtype)
        xw = (x.reshape(B * T, -1) @ params["W"] + params["b"]).reshape(B, T, -1)
        xw = xw.transpose(1, 0, 2)
        m = (mask.astype(x.dtype).T if mask is not None
             else jnp.ones((T, B), x.dtype))

        def step(c, inp):
            xw_t, m_t = inp
            h = self._act(xw_t + c["h"] @ params["RW"])
            h = _mask_carry(h, c["h"], m_t)
            return {"h": h}, h

        carry, hs = lax.scan(step, carry, (xw, m))
        return hs.transpose(1, 0, 2), carry


@register_layer
@dataclasses.dataclass(frozen=True)
class Bidirectional(Layer):
    """Bidirectional wrapper over any recurrent layer; merge modes CONCAT /
    ADD / MUL / AVERAGE (reference: GravesBidirectionalLSTM merges and the
    later Bidirectional wrapper)."""

    layer: Optional[Any] = None
    merge: str = "concat"
    # False = emit only the final state of each direction, merged (Keras
    # Bidirectional(..., return_sequences=False)): forward's last step with
    # backward's FULL-sequence state (which aligns with t=0) — NOT the last
    # timestep of the re-flipped backward output.
    return_sequences: bool = True

    def infer_n_in(self, input_type: InputType):
        return dataclasses.replace(self, layer=self.layer.infer_n_in(input_type))

    def with_defaults(self, **defaults):
        inner = self.layer.with_defaults(**defaults) if self.layer else self.layer
        return dataclasses.replace(super().with_defaults(**defaults), layer=inner)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        size = inner.size * 2 if self.merge == "concat" else inner.size
        if not self.return_sequences:
            return InputType.feed_forward(size)
        return InputType.recurrent(size, inner.timesteps)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        pf, sf = self.layer.init_params(kf, input_type, dtype)
        pb, sb = self.layer.init_params(kb, input_type, dtype)
        return {"fwd": pf, "bwd": pb}, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        rf = rb = None
        if rng is not None:
            rf, rb = jax.random.split(rng)
        yf, _ = self.layer.apply(params["fwd"], x, train=train, rng=rf, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.layer.apply(params["bwd"], xr, train=train, rng=rb, mask=mr)
        if not self.return_sequences:
            # Forward: last unmasked step. Backward: its own final scan step
            # (reversed time puts right-padding first, where the mask carries
            # the initial state through, so index -1 is the full-seq state).
            if mask is None:
                hf = yf[:, -1, :]
            else:
                idx = jnp.maximum(
                    jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
                hf = jnp.take_along_axis(
                    yf, idx[:, None, None], axis=1)[:, 0, :]
            hb = yb[:, -1, :]
            return self._merge(hf, hb), state
        yb = jnp.flip(yb, axis=1)
        return self._merge(yf, yb), state

    def _merge(self, yf, yb):
        if self.merge == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        if self.merge == "add":
            return yf + yb
        if self.merge == "mul":
            return yf * yb
        if self.merge in ("ave", "average"):
            return 0.5 * (yf + yb)
        raise ValueError(f"Unknown merge {self.merge!r}")


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(Layer):
    """Reference: `nn/layers/recurrent/GravesBidirectionalLSTM.java` —
    bidirectional peephole LSTM with concatenated fwd/bwd activations,
    implemented here as Bidirectional(GravesLSTM, merge=concat)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def _inner(self) -> Bidirectional:
        return Bidirectional(
            layer=GravesLSTM(
                n_in=self.n_in, n_out=self.n_out,
                activation=self.activation, weight_init=self.weight_init,
            ),
            merge="concat",
        )

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out * 2, input_type.timesteps)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self._inner().init_params(key, input_type, dtype)

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self._inner().apply(params, x, state=state, train=train, rng=rng, mask=mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(OutputLayer):
    """Per-timestep dense + loss over time. Reference:
    `nn/conf/layers/RnnOutputLayer.java` (3-D in/out, time-distributed W·x+b,
    masked loss)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def pre_output(self, params: Params, x):
        y = x @ params["W"]  # [B,T,nIn]@[nIn,nOut] batches on the MXU
        if self.has_bias:
            y = y + params["b"]
        return y

    def score(self, params, x, labels, mask=None):
        preout = self.pre_output(params, x)  # [B, T, nOut]
        return LossFunction.get(self.loss)(labels, preout, self.activation, mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class LastTimeStep(Layer):
    """Wrapper: emit only the last (unmasked) timestep of an RNN layer.
    Reference: `nn/conf/layers/recurrent/LastTimeStep` vertex/wrapper."""

    layer: Optional[Any] = None

    def infer_n_in(self, input_type: InputType):
        return dataclasses.replace(self, layer=self.layer.infer_n_in(input_type))

    def with_defaults(self, **defaults):
        inner = self.layer.with_defaults(**defaults) if self.layer else self.layer
        return dataclasses.replace(super().with_defaults(**defaults), layer=inner)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        return InputType.feed_forward(inner.size)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.layer.init_params(key, input_type, dtype)

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y, st = self.layer.apply(params, x, state=state, train=train, rng=rng, mask=mask)
        if mask is None:
            return y[:, -1, :], st
        idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)  # [B]
        return jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0, :], st
