"""Convolution / pooling / padding layers (NHWC, MXU-friendly).

Reference parity: `nn/conf/layers/{ConvolutionLayer,Convolution1DLayer,
SubsamplingLayer,Subsampling1DLayer,ZeroPaddingLayer}.java` + impls in
`nn/layers/convolution/` (im2col path + reflective cuDNN helper dispatch at
`ConvolutionLayer.java:67-77,164,318`). The helper seam is unnecessary here:
`jax.lax.conv_general_dilated` lowers straight to the TPU MXU, and XLA fuses
bias+activation into the conv — the TPU build's "cuDNN helper" IS the
compiler. ConvolutionMode Strict/Truncate/Same (reference
`nn/conf/ConvolutionMode.java`) maps to explicit VALID/SAME padding.

Layout: activations NHWC, kernels HWIO — the layouts XLA/TPU prefers (the
reference is NCHW/OIHW; translating that would cost transposes on TPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, Params, register_layer


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_size(size: int, k: int, s: int, p: int, mode: str) -> int:
    if mode == "same":
        return -(-size // s)  # ceil
    if mode == "strict":
        if (size + 2 * p - k) % s != 0:
            raise ValueError(
                f"ConvolutionMode=strict: (size {size} + 2*pad {p} - kernel {k}) "
                f"not divisible by stride {s} (reference: ConvolutionMode.Strict)"
            )
        return (size + 2 * p - k) // s + 1
    # truncate (reference default tolerates remainder)
    return (size + 2 * p - k) // s + 1


def _padding_2d(mode: str, kernel, stride, pad) -> Any:
    if mode == "same":
        return "SAME"
    kh, kw = _pair(kernel)
    ph, pw = _pair(pad)
    return [(ph, ph), (pw, pw)]


@register_layer
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(Layer):
    """2-D convolution. Reference: `nn/conf/layers/ConvolutionLayer.java`,
    impl `nn/layers/convolution/ConvolutionLayer.java` (im2col+gemm or cuDNN
    helper — here one `lax.conv_general_dilated` on the MXU)."""

    n_in: Optional[int] = None       # input channels
    n_out: Optional[int] = None      # output channels
    kernel: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    dilation: Any = (1, 1)
    convolution_mode: str = "truncate"   # strict | truncate | same
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType) -> "ConvolutionLayer":
        if self.n_in is None and input_type.kind in ("cnn", "cnn_flat"):
            return dataclasses.replace(self, n_in=input_type.channels)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        m = self.convolution_mode
        h = _out_size(input_type.height, kh, sh, ph, m)
        w = _out_size(input_type.width, kw, sw, pw, m)
        return InputType.convolutional(h, w, self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        w = self._winit()(key, (kh, kw, self.n_in, self.n_out), dtype)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return params, {}

    def pre_output(self, params: Params, x):
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_padding_2d(self.convolution_mode, self.kernel, self.stride, self.padding),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._maybe_dropout(x, train, rng)
        return self._act(self.pre_output(params, x)), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed convolution (reference: Deconvolution2D config)."""

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            h, w = input_type.height * sh, input_type.width * sw
        else:
            h = sh * (input_type.height - 1) + kh - 2 * ph
            w = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(h, w, self.n_out)

    def pre_output(self, params: Params, x):
        pad = ("SAME" if self.convolution_mode == "same"
               else [(p, p) for p in _pair(self.padding)])
        y = lax.conv_transpose(
            x, params["W"],
            strides=_pair(self.stride),
            padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return y


@register_layer
@dataclasses.dataclass(frozen=True)
class DepthwiseConvolution2DLayer(Layer):
    """Depthwise conv (reference: DepthwiseConvolution2D). Implemented via
    feature_group_count = n_in, which XLA lowers efficiently on TPU."""

    n_in: Optional[int] = None
    depth_multiplier: int = 1
    kernel: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.channels)
        return self

    @property
    def n_out(self):
        return self.n_in * self.depth_multiplier

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        m = self.convolution_mode
        return InputType.convolutional(
            _out_size(input_type.height, kh, sh, ph, m),
            _out_size(input_type.width, kw, sw, pw, m),
            self.n_out,
        )

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        w = self._winit()(key, (kh, kw, 1, self.n_out), dtype)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_padding_2d(self.convolution_mode, self.kernel, self.stride, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in,
        )
        if self.has_bias:
            y = y + params["b"]
        return self._act(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SeparableConvolution2DLayer(Layer):
    """Depthwise-separable conv (reference: SeparableConvolution2D)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    depth_multiplier: int = 1
    kernel: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.channels)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        m = self.convolution_mode
        return InputType.convolutional(
            _out_size(input_type.height, kh, sh, ph, m),
            _out_size(input_type.width, kw, sw, pw, m),
            self.n_out,
        )

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        k1, k2 = jax.random.split(key)
        mid = self.n_in * self.depth_multiplier
        params = {
            "dW": self._winit()(k1, (kh, kw, 1, mid), dtype),
            "pW": self._winit()(k2, (1, 1, mid, self.n_out), dtype),
        }
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y = lax.conv_general_dilated(
            x, params["dW"],
            window_strides=_pair(self.stride),
            padding=_padding_2d(self.convolution_mode, self.kernel, self.stride, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in,
        )
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self._act(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Spatial pooling. Reference: `nn/conf/layers/SubsamplingLayer.java`
    (PoolingType MAX/AVG/SUM/PNORM), impl `nn/layers/convolution/subsampling/`.
    One `lax.reduce_window` — no cuDNN helper needed."""

    pooling: str = "max"             # max | avg | sum | pnorm
    kernel: Any = (2, 2)
    stride: Any = (2, 2)
    padding: Any = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        m = self.convolution_mode
        return InputType.convolutional(
            _out_size(input_type.height, kh, sh, ph, m),
            _out_size(input_type.width, kw, sw, pw, m),
            input_type.channels,
        )

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = _pair(self.padding)
            pad = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        p = self.pooling.lower()
        if p == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif p == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif p == "avg":
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        elif p == "pnorm":
            s = lax.reduce_window(
                jnp.abs(x) ** self.pnorm, 0.0, lax.add, dims, strides, pad
            )
            y = s ** (1.0 / self.pnorm)
        else:
            raise ValueError(f"Unknown pooling {self.pooling!r}")
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(Layer):
    """Reference: `nn/conf/layers/ZeroPaddingLayer.java`."""

    pad: Any = (1, 1)  # (ph, pw) or ((top,bottom),(left,right))

    def _pads(self):
        p = self.pad
        if isinstance(p, (tuple, list)) and len(p) == 2 and isinstance(p[0], (tuple, list)):
            return tuple(p[0]), tuple(p[1])
        ph, pw = _pair(p)
        return (ph, ph), (pw, pw)

    def output_type(self, input_type: InputType) -> InputType:
        (pt, pb), (pl, pr) = self._pads()
        return InputType.convolutional(
            input_type.height + pt + pb, input_type.width + pl + pr, input_type.channels
        )

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        (pt, pb), (pl, pr) = self._pads()
        return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0))), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Cropping2DLayer(Layer):
    """Reference: Cropping2D config."""

    crop: Any = (0, 0)

    def _crops(self):
        c = self.crop
        if isinstance(c, (tuple, list)) and len(c) == 2 and isinstance(c[0], (tuple, list)):
            return tuple(c[0]), tuple(c[1])
        ch, cw = _pair(c)
        return (ch, ch), (cw, cw)

    def output_type(self, input_type: InputType) -> InputType:
        (ct, cb), (cl, cr) = self._crops()
        return InputType.convolutional(
            input_type.height - ct - cb, input_type.width - cl - cr, input_type.channels
        )

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        (ct, cb), (cl, cr) = self._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, ct:h - cb, cl:w - cr, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class Upsampling2DLayer(Layer):
    """Nearest-neighbor upsampling (reference: Upsampling2D)."""

    size: Any = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        sh, sw = _pair(self.size)
        return InputType.convolutional(
            input_type.height * sh, input_type.width * sw, input_type.channels
        )

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SpaceToDepthLayer(Layer):
    """Fold `block`×`block` spatial tiles into channels:
    [B, H, W, C] -> [B, H/b, W/b, b*b*C], channel order (dy, dx, c).

    TPU-native extension (no counterpart in the 0.9-era reference; later
    DL4J adds SpaceToDepthLayer): the MXU reads 128-channel tiles, so a
    stem conv over 3-channel images wastes >95% of the systolic array —
    folding space into channels first (with the stem kernel folded to
    match, see zoo/resnet.py `fold_stem_kernel`) is the standard MLPerf
    ResNet optimization."""

    block: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.block
        if input_type.height % b or input_type.width % b:
            raise ValueError(
                f"SpaceToDepth block {b} must divide spatial dims "
                f"({input_type.height}x{input_type.width})")
        return InputType.convolutional(
            input_type.height // b, input_type.width // b,
            input_type.channels * b * b)

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None):
        b = self.block
        B, H, W, C = x.shape
        y = x.reshape(B, H // b, b, W // b, b, C)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, H // b, W // b, b * b * C)
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class Convolution1DLayer(Layer):
    """1-D (temporal) conv over [batch, time, features]. Reference:
    `nn/conf/layers/Convolution1DLayer.java`."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    kernel: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "same"
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = _out_size(t, self.kernel, self.stride, self.padding, self.convolution_mode)
        return InputType.recurrent(self.n_out, t)

    def init_params(self, key, input_type, dtype=jnp.float32):
        w = self._winit()(key, (self.kernel, self.n_in, self.n_out), dtype)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        pad = ("SAME" if self.convolution_mode == "same"
               else [(self.padding, self.padding)])
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self._act(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(Layer):
    """1-D pooling over [batch, time, features]. Reference:
    `nn/conf/layers/Subsampling1DLayer.java`."""

    pooling: str = "max"
    kernel: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = _out_size(t, self.kernel, self.stride, self.padding, self.convolution_mode)
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = [(0, 0), (self.padding, self.padding), (0, 0)]
        dims, strides = (1, self.kernel, 1), (1, self.stride, 1)
        if self.pooling == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class FusedConvBNLayer(Layer):
    """Conv + batch norm + activation as ONE fused op (Pallas): the
    BN batch statistics are accumulated inside the conv kernel while
    the output tile is in VMEM, saving a full HBM sweep per conv+BN pair
    (see `ops/conv_fused.py`). This is the framework's answer to the
    reference's cuDNN helper seam (`ConvolutionLayer.java:67-77`,
    `CudnnBatchNormalizationHelper.java`). Two kernel shapes are fused:
    (1, 1) any stride (the ResNet bottleneck reduce/expand/projection
    matmuls) and (3, 3) stride-1 SAME (the bottleneck middle convs).

    Parameters: W [kh, kw, n_in, n_out] (HWIO, same shape as
    ConvolutionLayer's), gamma/beta; state: running mean/var. Equivalent
    to ConvolutionLayer(kernel, has_bias=False, activation=identity)
    followed by BatchNormalization(activation=...), to float32 accuracy.
    """

    CONSUMES = "cnn"   # drives preprocessor auto-insertion (NHWC input)

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    kernel: Any = (1, 1)
    stride: Any = (1, 1)
    decay: float = 0.9
    eps: float = 1e-5

    def __post_init__(self):
        k = _pair(self.kernel)
        if k not in ((1, 1), (3, 3)):
            raise ValueError(f"FusedConvBNLayer supports kernels (1,1) "
                             f"and (3,3), got {k}")
        if k == (3, 3) and _pair(self.stride) != (1, 1):
            raise ValueError("the fused 3x3 path is stride-1 SAME only")

    def infer_n_in(self, input_type: InputType) -> "FusedConvBNLayer":
        if self.n_in is None and input_type.kind in ("cnn", "cnn_flat"):
            return dataclasses.replace(self, n_in=input_type.channels)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        # (1,1): stride applies as input subsampling, out = ceil(in/s),
        # identical to a VALID-padded strided 1x1 conv. (3,3): stride-1
        # SAME, spatial dims unchanged.
        sh, sw = _pair(self.stride)
        return InputType.convolutional(
            -(-input_type.height // sh), -(-input_type.width // sw),
            self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        w = self._winit()(key, (kh, kw, self.n_in, self.n_out), dtype)
        params = {
            "W": w,
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }
        state = {"mean": jnp.zeros((self.n_out,), jnp.float32),
                 "var": jnp.ones((self.n_out,), jnp.float32)}
        return params, state

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None):
        from deeplearning4j_tpu.ops.conv_fused import (
            conv1x1_bn_act, conv3x3_bn_act)

        x = self._maybe_dropout(x, train, rng)
        act = self.activation or "identity"
        relu = act == "relu"
        interpret = jax.default_backend() != "tpu"
        is3x3 = _pair(self.kernel) == (3, 3)
        if train:
            if is3x3:
                out, m, v = conv3x3_bn_act(
                    x, params["W"], params["gamma"], params["beta"],
                    eps=self.eps, relu=relu, train=True,
                    interpret=interpret)
            else:
                out, m, v = conv1x1_bn_act(
                    x, params["W"][0, 0], params["gamma"], params["beta"],
                    stride=_pair(self.stride), eps=self.eps, relu=relu,
                    train=True, interpret=interpret)
            d = self.decay
            new_state = {
                "mean": d * state["mean"] + (1 - d) * m,
                "var": d * state["var"] + (1 - d) * v,
            }
        else:
            if is3x3:
                out = conv3x3_bn_act(
                    x, params["W"], params["gamma"], params["beta"],
                    mean=state["mean"], var=state["var"],
                    eps=self.eps, relu=relu, train=False)
            else:
                out = conv1x1_bn_act(
                    x, params["W"][0, 0], params["gamma"], params["beta"],
                    mean=state["mean"], var=state["var"],
                    stride=_pair(self.stride), eps=self.eps, relu=relu,
                    train=False)
            new_state = state
        if not relu and act != "identity":
            out = self._act(out)
        return out, new_state
