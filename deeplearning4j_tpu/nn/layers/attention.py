"""Attention layers — modern extension (the RNN-era reference has none;
required so the framework serves transformer-class models at TPU scale,
per the project charter's long-context mandate).

MultiHeadAttention follows this framework's Layer contract so it composes
with MultiLayerNetwork/ComputationGraph like any reference layer. When a
mesh+seq axis is configured (see `parallel.ring_attention`), the same layer
runs sequence-parallel without code changes — the attention core is swapped.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.parallel.ring_attention import attention


@register_layer
@dataclasses.dataclass(frozen=True)
class MultiHeadAttention(Layer):
    """Self-attention over [batch, time, features]."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None       # model dim (defaults to n_in)
    num_heads: int = 4
    causal: bool = False
    attn_dropout: float = 0.0

    def infer_n_in(self, input_type: InputType):
        upd = {}
        if self.n_in is None:
            upd["n_in"] = input_type.size
        if self.n_out is None:
            upd["n_out"] = upd.get("n_in", self.n_in)
        return dataclasses.replace(self, **upd) if upd else self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def init_params(self, key, input_type, dtype=jnp.float32):
        d = self.n_out
        if d % self.num_heads:
            raise ValueError(
                f"n_out {d} not divisible by num_heads {self.num_heads}")
        ks = jax.random.split(key, 4)
        winit = self._winit()
        return {
            "Wq": winit(ks[0], (self.n_in, d), dtype),
            "Wk": winit(ks[1], (self.n_in, d), dtype),
            "Wv": winit(ks[2], (self.n_in, d), dtype),
            "Wo": winit(ks[3], (d, d), dtype),
            "b": jnp.zeros((d,), dtype),
        }, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        B, T, _ = x.shape
        H = self.num_heads
        Dh = self.n_out // H

        def split(w):
            return (x @ w).reshape(B, T, H, Dh)

        q, k, v = split(params["Wq"]), split(params["Wk"]), split(params["Wv"])
        if mask is not None:
            # Padding mask: large negative bias on masked keys before softmax
            # (combined with the causal band when both apply).
            o = self._masked_attention(q, k, v, mask, self.causal)
        elif (not train and jax.default_backend() == "tpu" and T % 128 == 0):
            # Fused blockwise kernel (ops/attention.py), inference only: its
            # backward is a dense recompute, so training keeps the XLA path.
            from deeplearning4j_tpu.ops.attention import flash_attention

            o = flash_attention(q, k, v, self.causal)
        else:
            o = attention(q, k, v, causal=self.causal)
        o = o.reshape(B, T, self.n_out)
        y = o @ params["Wo"] + params["b"]
        return self._act(y), state

    @staticmethod
    def _masked_attention(q, k, v, mask, causal=False):
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d)
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e30)
        if causal:
            t = s.shape[-1]
            band = jnp.tril(jnp.ones((t, t), jnp.bool_))
            bias = bias + jnp.where(band[None, None], 0.0, -1e30)
        p = jax.nn.softmax(s + bias, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)
