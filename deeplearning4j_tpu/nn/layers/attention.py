"""Attention layers — modern extension (the RNN-era reference has none;
required so the framework serves transformer-class models at TPU scale,
per the project charter's long-context mandate).

MultiHeadAttention follows this framework's Layer contract so it composes
with MultiLayerNetwork/ComputationGraph like any reference layer. When a
mesh+seq axis is configured (see `parallel.ring_attention`), the same layer
runs sequence-parallel without code changes — the attention core is swapped.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.parallel.ring_attention import attention


def rope_rotate(x, positions, base: float = 10000.0):
    """Rotary position embedding (RoPE): rotate [B, T, H, Dh] per-head
    pairs by position-dependent angles. Attention scores between rotated
    q/k depend only on RELATIVE distance, so there is no learned
    position table and no absolute-length cap (modern extension; the
    RNN-era reference has no positional encodings at all).

    `positions` is [T] (one stream, or all rows at the same offset) or
    [B, T] (per-row offsets — the slot-indexed decode path, where each
    session in the batch sits at its own absolute position)."""
    dh = x.shape[-1]
    if dh % 2:
        raise ValueError(f"RoPE needs an even head dim, got {dh}")
    half = dh // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    if ang.ndim == 2:                  # [T, half] -> [1, T, half]
        ang = ang[None]
    c = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    s = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


@register_layer
@dataclasses.dataclass(frozen=True)
class MultiHeadAttention(Layer):
    """Self-attention over [batch, time, features].

    `num_kv_heads < num_heads` enables grouped-query attention (GQA):
    K/V project to fewer heads and each group of `num_heads //
    num_kv_heads` query heads shares one KV head. The KV cache (and its
    per-token decode HBM traffic — the binding resource of
    autoregressive decoding on TPU) shrinks by the group factor;
    num_kv_heads=1 is multi-query attention. Modern extension (the
    RNN-era reference has no attention); default (None) is standard MHA.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None       # model dim (defaults to n_in)
    num_heads: int = 4
    num_kv_heads: Optional[int] = None  # None -> num_heads (standard MHA)
    causal: bool = False
    attn_dropout: float = 0.0
    max_cache: int = 1024             # KV-cache length for decode stepping
    rope: bool = False                # rotary position embedding on q/k
    window: Optional[int] = None      # sliding-window (local) attention:
    # each position sees at most `window` keys back (causal) or within
    # |i-j| < window (bidirectional) — Mistral-style locality; O(T*w)
    # useful score mass. Windowed layers route through the banded Pallas
    # kernel when `kernel_defaults.banded_policy` approves (O(T*w) by
    # grid construction), else the dense band-masked path; the flash
    # kernel and the ring remain full-context codepaths.
    rolling_cache: bool = False       # causal+window decode streams in a
    # FIXED max_cache-slot ring buffer (Mistral's rolling KV cache):
    # slot = position % max_cache, so generation length is unbounded in
    # O(window) memory. Each step needs max_cache >= T + window - 1.

    def infer_n_in(self, input_type: InputType):
        upd = {}
        if self.n_in is None:
            upd["n_in"] = input_type.size
        if self.n_out is None:
            upd["n_out"] = upd.get("n_in", self.n_in)
        return dataclasses.replace(self, **upd) if upd else self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    @property
    def _kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    def _check_heads(self):
        H, Hkv = self.num_heads, self._kv_heads
        if self.n_out % H:
            raise ValueError(
                f"n_out {self.n_out} not divisible by num_heads {H}")
        if not 1 <= Hkv <= H or H % Hkv:
            raise ValueError(
                f"num_kv_heads {Hkv} must divide num_heads {H}")

    def init_params(self, key, input_type, dtype=jnp.float32):
        d = self.n_out
        self._check_heads()
        if self.window is not None and self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.rolling_cache:
            if self.window is None or not self.causal:
                raise ValueError(
                    "rolling_cache needs causal=True and a window (the "
                    "ring buffer only ever holds the last `window` keys)")
            if self.max_cache < self.window:
                raise ValueError(
                    f"rolling_cache: max_cache {self.max_cache} < window "
                    f"{self.window}; the buffer cannot hold the band")
        dkv = self._kv_heads * (d // self.num_heads)
        ks = jax.random.split(key, 4)
        winit = self._winit()
        return {
            "Wq": winit(ks[0], (self.n_in, d), dtype),
            "Wk": winit(ks[1], (self.n_in, dkv), dtype),
            "Wv": winit(ks[2], (self.n_in, dkv), dtype),
            "Wo": winit(ks[3], (d, d), dtype),
            "b": jnp.zeros((d,), dtype),
        }, {}

    def decode_carry(self, batch: int, dtype=jnp.float32, *,
                     per_slot: bool = False, kv_dtype: str = None,
                     page_len: int = None, pages: int = None):
        """Preallocated KV cache for incremental decoding (the transformer
        analogue of the reference's rnnTimeStep statefulness,
        `MultiLayerNetwork.java:rnnTimeStep`): fixed [B, max_cache, Hkv,
        Dh] buffers + a write position, so every step reuses one compiled
        program instead of growing shapes. Under GQA the cache holds only
        the Hkv KV heads — the group factor comes straight off decode's
        per-token HBM traffic.

        `per_slot=True` makes the write position a [batch] vector — each
        batch row is an independent decode SLOT at its own position
        (serving sessions: rows advance at different rates, inactive
        lanes stand still). Requires causal attention.

        `kv_dtype` in ("int8", "fp8") stores K/V quantized with one f32
        scale per (token, kv-head) — `scale_k`/`scale_v` rows of
        [B, L, Hkv] ride the carry next to the caches. Quantize-on-write
        and dequantize-on-read live in `_decode`; the scale rows cost
        1/Dh of a native cache, so slots-per-chip multiplies by
        ~4·Dh/(Dh+4) at int8.

        `page_len` switches the storage to PAGED layout: a pool of
        `pages` fixed-size KV blocks `[P, page_len, Hkv, Dh]` plus a
        per-slot `page_table` [B, max_cache/page_len] int32 mapping each
        logical page to a physical block. Positions stay LOGICAL —
        `_decode` translates position -> (page_table[pos // page_len],
        pos % page_len) at the scatter/gather, so visibility arithmetic
        and RoPE are unchanged and page indices ride the trace like slot
        ids (zero recompiles under page churn). This is the KVSlotPool's
        prefix-cache layout: sessions sharing a prompt prefix point their
        tables at the same refcounted physical blocks. Requires per_slot
        and a non-rolling cache (the ring's held-index arithmetic
        addresses the monolithic slot layout). `pages` defaults to
        `batch * max_cache / page_len` — the same memory as the
        monolithic layout."""
        Dh = self.n_out // self.num_heads
        L = self.max_cache
        Hkv = self._kv_heads
        if per_slot and not self.causal:
            raise ValueError(
                "per-slot decode carries need causal=True (each lane's "
                "visible prefix is its own position)")
        cdt = dtype
        if kv_dtype in ("int8", "fp8"):
            if not per_slot:
                raise ValueError(
                    "quantized KV carries are a session-pool feature "
                    "(per_slot=True); the lockstep rnn_time_step path "
                    "stays native")
            cdt = jnp.int8 if kv_dtype == "int8" else jnp.float8_e4m3fn
        elif kv_dtype not in (None, "native"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
        if page_len is not None:
            if not per_slot:
                raise ValueError(
                    "paged KV carries are a session-pool feature "
                    "(per_slot=True)")
            if self.rolling_cache:
                raise ValueError(
                    "paged KV carries cannot ride a rolling ring: the "
                    "ring's held-index arithmetic addresses the "
                    "monolithic slot layout")
            if page_len < 1 or L % page_len:
                raise ValueError(
                    f"max_cache {L} not divisible by page_len {page_len}")
            npg = L // page_len
            P = int(pages) if pages is not None else batch * npg
            if P < npg:
                raise ValueError(
                    f"page pool of {P} blocks cannot hold even one "
                    f"slot's {npg} logical pages")
            carry = {
                "cache_k": jnp.zeros((P, page_len, Hkv, Dh), cdt),
                "cache_v": jnp.zeros((P, page_len, Hkv, Dh), cdt),
                "page_table": jnp.zeros((batch, npg), jnp.int32),
                "pos": jnp.zeros((batch,), jnp.int32),
            }
            if kv_dtype in ("int8", "fp8"):
                carry["scale_k"] = jnp.zeros((P, page_len, Hkv),
                                             jnp.float32)
                carry["scale_v"] = jnp.zeros((P, page_len, Hkv),
                                             jnp.float32)
            return carry
        carry = {
            "cache_k": jnp.zeros((batch, L, Hkv, Dh), cdt),
            "cache_v": jnp.zeros((batch, L, Hkv, Dh), cdt),
            "pos": jnp.zeros((batch,) if per_slot else (), jnp.int32),
        }
        if kv_dtype in ("int8", "fp8"):
            carry["scale_k"] = jnp.zeros((batch, L, Hkv), jnp.float32)
            carry["scale_v"] = jnp.zeros((batch, L, Hkv), jnp.float32)
        return carry

    def _decode(self, params, x, state, mask=None):
        """One decode step: append this block's K/V at `pos`, attend the
        incoming queries over the visible cache prefix.

        Two position layouts share this method (and one compiled program
        each): a SCALAR `pos` carry steps every batch row in lockstep
        (the classic `rnn_time_step` path — `mask` is ignored, as
        before), while a VECTOR `pos` carry ([B]) steps slot-indexed
        session lanes independently. In vector mode `mask` is a [B, T]
        prefix-validity mask: padded tokens are dropped from the cache
        write (scatter index pushed out of range, `mode="drop"`) and do
        not advance the row's position, so a prefill chunk and a
        single-token step can share one padded bucket shape.

        A `page_table` in the carry switches both the scatter and the
        reads to PAGED addressing (see `decode_carry`): logical position
        j lives at physical row `page_table[j // Lp]`, offset `j % Lp`.
        Everything position-flavored — visibility, RoPE, overflow
        poison — keeps operating on logical positions, so the paged and
        monolithic layouts are bit-identical by construction."""
        B, T, _ = x.shape
        H = self.num_heads
        Hkv = self._kv_heads
        Dh = self.n_out // H
        paged = "page_table" in state
        if paged:
            if self.rolling_cache:
                raise ValueError(
                    "paged KV caches cannot ride a rolling ring")
            pt = state["page_table"]                   # [B, NP] int32
            npg = pt.shape[1]
            Lp = state["cache_k"].shape[1]
            L = npg * Lp
        else:
            L = state["cache_k"].shape[1]
        if self.rolling_cache:
            # per-step feasibility is static: the T new keys plus the
            # window tail of the oldest query must coexist in the ring
            if T + self.window - 1 > L:
                raise ValueError(
                    f"rolling decode step of {T} tokens needs max_cache "
                    f">= {T + self.window - 1} (window {self.window}), "
                    f"have {L}")
        elif T > L:
            raise ValueError(f"decode step of {T} tokens > max_cache {L}")
        pos = state["pos"]
        per_slot = getattr(pos, "ndim", 0) == 1
        if per_slot and not self.causal:
            raise ValueError("per-slot decode needs causal=True")
        if paged and not per_slot:
            raise ValueError("paged KV caches require per-slot mode")
        quant = "scale_k" in state
        if quant and not per_slot:
            raise ValueError("quantized KV carries require per-slot mode")
        if (not self.rolling_cache and not per_slot
                and not isinstance(pos, jax.core.Tracer)
                and int(pos) + T > L):
            raise ValueError(
                f"KV cache overflow: pos {int(pos)} + step {T} > "
                f"max_cache {L}; raise max_cache or clear state")

        def split(w, heads):
            return (x @ w).reshape(B, T, heads, Dh)

        q = split(params["Wq"], H)
        k = split(params["Wk"], Hkv)
        v = split(params["Wv"], Hkv)
        if per_slot:
            valid = None if mask is None else (mask > 0)       # [B, T]
            n_new = (jnp.full(pos.shape, T, pos.dtype) if valid is None
                     else valid.sum(axis=1).astype(pos.dtype))  # [B]
            q_ids = pos[:, None] + jnp.arange(T)               # [B, T]
            if self.rope:
                q = rope_rotate(q, q_ids)
                k = rope_rotate(k, q_ids)
            rows = jnp.arange(B)[:, None]
            tgt = q_ids % L if self.rolling_cache else q_ids
            if valid is not None:
                # padded tokens scatter out of range -> dropped, so a
                # short chunk in a wide bucket never dirties the cache
                tgt = jnp.where(valid, tgt, L)
            if paged:
                # logical target -> (physical page, in-page offset);
                # padded/overflowing rows land at offset Lp, out of the
                # page dim's bounds, so mode="drop" keeps them out
                # exactly like the monolithic layout's tgt >= L. The
                # page indices are traced gathers from the carry —
                # page churn never mints a new program.
                i0 = pt[rows, jnp.clip(tgt // Lp, 0, npg - 1)]  # [B, T]
                i1 = jnp.where(tgt < L, tgt % Lp, Lp)
            else:
                i0, i1 = rows, tgt
            cdt = state["cache_k"].dtype
            if quant:
                # quantize-on-write: one f32 scale per (token, kv-head),
                # amax-scaled to the storage format's dynamic range.
                # Zero-amax rows keep scale 1 so dequant stays finite.
                qmax = 127.0 if cdt == jnp.int8 else 448.0

                def _q(val):
                    amax = jnp.max(jnp.abs(val), axis=-1)      # [B, T, Hkv]
                    sc = jnp.where(amax > 0.0, amax / qmax, 1.0)
                    scaled = val.astype(jnp.float32) / sc[..., None]
                    if cdt == jnp.int8:
                        qv = jnp.clip(jnp.round(scaled), -127.0,
                                      127.0).astype(jnp.int8)
                    else:
                        qv = scaled.astype(cdt)
                    return qv, sc.astype(jnp.float32)

                kq, sk = _q(k)
                vq, sv = _q(v)
                ck = state["cache_k"].at[i0, i1].set(kq, mode="drop")
                cv = state["cache_v"].at[i0, i1].set(vq, mode="drop")
                csk = state["scale_k"].at[i0, i1].set(sk, mode="drop")
                csv = state["scale_v"].at[i0, i1].set(sv, mode="drop")
            else:
                ck = state["cache_k"].at[i0, i1].set(
                    k.astype(cdt), mode="drop")
                cv = state["cache_v"].at[i0, i1].set(
                    v.astype(cdt), mode="drop")
            if self.rolling_cache:
                # per-row held-position arithmetic (see scalar branch)
                end = pos + n_new - 1                          # [B]
                j = jnp.arange(L)[None, :]
                held = end[:, None] - ((end[:, None] - j) % L)  # [B, L]
                held = held[:, None, :]                     # [B, 1, L]
                qe = q_ids[:, :, None]                      # [B, T, 1]
                vis = ((held >= 0) & (held <= qe)
                       & (held > qe - self.window))         # [B, T, L]
            else:
                # per-row overflow poison (tracer-safe, like scalar)
                q = jnp.where((pos + n_new <= L)[:, None, None, None],
                              q, jnp.nan)
                k_ids = jnp.arange(L)[None, None, :]
                qe = q_ids[:, :, None]
                vis = k_ids <= qe
                if self.window is not None:
                    vis = vis & (k_ids > qe - self.window)
            pos_new = pos + n_new
        elif self.rolling_cache:
            # Mistral-style ring buffer: slot = global position mod L.
            # The write is a scatter (it may wrap the boundary); each
            # slot's CURRENT occupant is recovered arithmetically from
            # the newest written global position, so visibility needs no
            # stored metadata.
            if self.rope:
                positions = pos + jnp.arange(T)
                q = rope_rotate(q, positions)
                k = rope_rotate(k, positions)
            slots = (pos + jnp.arange(T)) % L
            ck = state["cache_k"].at[:, slots].set(
                k.astype(state["cache_k"].dtype))
            cv = state["cache_v"].at[:, slots].set(
                v.astype(state["cache_v"].dtype))
            end = pos + T - 1               # newest written global pos
            j = jnp.arange(L)
            held = end - ((end - j) % L)    # global pos held in slot j
            q_ids = pos + jnp.arange(T)[:, None]
            vis = ((held[None, :] >= 0)     # slot ever written
                   & (held[None, :] <= q_ids)          # causal
                   & (held[None, :] > q_ids - self.window))
            pos_new = pos + T
        else:
            # Tracer-safe overflow poison: under jit the eager check
            # above cannot fire, and dynamic_update_slice would silently
            # clamp the write into the last rows — poison the output
            # with NaN instead so overflow is loud, not wrong.
            if self.rope:
                # rotate with ABSOLUTE positions continuing from the
                # carry; the cache stores rotated keys (standard RoPE)
                positions = pos + jnp.arange(T)
                q = rope_rotate(q, positions)
                k = rope_rotate(k, positions)
            q = jnp.where(pos + T <= L, q, jnp.nan)
            z = jnp.zeros((), pos.dtype)   # index dtypes must match `pos`
            ck = jax.lax.dynamic_update_slice(
                state["cache_k"], k.astype(state["cache_k"].dtype),
                (z, pos, z, z))
            cv = jax.lax.dynamic_update_slice(
                state["cache_v"], v.astype(state["cache_v"].dtype),
                (z, pos, z, z))
            k_ids = jnp.arange(L)[None, :]
            q_ids = pos + jnp.arange(T)[:, None]
            # causal: each new query sees cache + itself; non-causal:
            # the whole written prefix (never the unwritten tail)
            vis = k_ids <= q_ids if self.causal else k_ids < pos + T
            if self.window is not None:
                # sliding window: `window` keys back; bidirectional also
                # bounds the forward side (|i-j| < window, matching the
                # dense band — still never past the written prefix)
                vis = vis & (k_ids > q_ids - self.window)
                if not self.causal:
                    vis = vis & (k_ids < q_ids + self.window)
            pos_new = pos + T
        # [T, L] (lockstep) or [B, T, L] (per-slot) -> broadcastable
        vb = vis if vis.ndim == 3 else vis[None]
        if paged:
            # logical [B, L, Hkv, Dh] view for the dense paths: gather
            # each slot's page chain back into position order. The
            # banded kernel below never materializes this — its
            # BlockSpec index_map reads the page table directly.
            ck_r = jnp.take(ck, pt, axis=0).reshape(B, L, Hkv, Dh)
            cv_r = jnp.take(cv, pt, axis=0).reshape(B, L, Hkv, Dh)
            csk_r = (jnp.take(csk, pt, axis=0).reshape(B, L, Hkv)
                     if quant else None)
            csv_r = (jnp.take(csv, pt, axis=0).reshape(B, L, Hkv)
                     if quant else None)
        else:
            ck_r, cv_r = ck, cv
            csk_r, csv_r = (csk, csv) if quant else (None, None)
        if quant:
            # dequantize-on-read for the dense fallback: the banded
            # kernel path below instead fuses this product into its
            # block loads and never materializes the f32 cache
            ck_a = ck_r.astype(q.dtype) * csk_r.astype(q.dtype)[..., None]
            cv_a = cv_r.astype(q.dtype) * csv_r.astype(q.dtype)[..., None]
        else:
            ck_a, cv_a = ck_r, cv_r
        dpol = None
        if T == 1:
            from deeplearning4j_tpu.ops.kernel_defaults import (
                decode_attention_policy,
            )

            dpol = decode_attention_policy(L, H, Hkv)
        use_banded = dpol is not None and dpol.kind == "banded"
        if use_banded and paged and jax.default_backend() == "tpu" \
                and Lp % 128:
            # the paged kernel's cache block IS one page; a page that
            # Mosaic cannot tile falls back to the dense gather
            use_banded = False
        if use_banded:
            # Single-token step: the banded decode kernel reads the cache
            # in its stored [*, L, Hkv, Dh] layout (same arithmetic as
            # `vis` above, held-index ring included) without broadcasting
            # KV to H heads or materializing [B, H, 1, L] scores in HBM.
            # Paged carries route to the paged variant: the page table
            # rides the scalar-prefetch lane and the kernel's index_map
            # resolves logical block -> physical page, so shared-prefix
            # sessions read the same HBM blocks with no gather.
            if per_slot:
                dec_pos = pos
                dec_end = (pos + n_new - 1 if self.rolling_cache
                           else pos)
            else:
                dec_pos = jnp.broadcast_to(pos, (B,))
                dec_end = dec_pos
            if paged:
                from deeplearning4j_tpu.ops.banded_attention import (
                    paged_decode_attention,
                )

                o = paged_decode_attention(
                    q[:, 0], ck, cv, pt, dec_pos.astype(jnp.int32),
                    window=self.window,
                    interpret=jax.default_backend() != "tpu",
                    scale_k=csk if quant else None,
                    scale_v=csv if quant else None)
            else:
                from deeplearning4j_tpu.ops.banded_attention import (
                    banded_decode_attention,
                )

                o = banded_decode_attention(
                    q[:, 0], ck, cv, dec_pos.astype(jnp.int32),
                    dec_end.astype(jnp.int32), window=self.window,
                    rolling=self.rolling_cache, block_l=dpol.block_l,
                    interpret=jax.default_backend() != "tpu",
                    scale_k=csk if quant else None,
                    scale_v=csv if quant else None)
            o = o[:, None]
        elif Hkv != H:
            # GQA: group the query heads against the Hkv-wide cache in
            # the einsum itself — the cache is never broadcast to H
            # heads, so the per-token HBM sweep (decode's binding
            # resource) really is Hkv/H of full MHA
            G = H // Hkv
            qg = q.reshape(B, T, Hkv, G, Dh)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck_a) / jnp.sqrt(Dh)
            s = jnp.where(vb[:, None, None], s, -1e30)
            o = jnp.einsum("bhgqk,bkhd->bqhgd",
                           jax.nn.softmax(s, axis=-1), cv_a)
            o = o.reshape(B, T, H, Dh)
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q, ck_a) / jnp.sqrt(Dh)
            s = jnp.where(vb[:, None], s, -1e30)
            o = jnp.einsum("bhqk,bkhd->bqhd",
                           jax.nn.softmax(s, axis=-1), cv_a)
        y = o.reshape(B, T, self.n_out) @ params["Wo"] + params["b"]
        new_state = {"cache_k": ck, "cache_v": cv, "pos": pos_new}
        if paged:
            new_state["page_table"] = pt
        if quant:
            new_state["scale_k"] = csk
            new_state["scale_v"] = csv
        return self._act(y), new_state

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if state is not None and "cache_k" in state:
            return self._decode(params, x, state, mask=mask)
        B, T, _ = x.shape
        H = self.num_heads
        Hkv = self._kv_heads
        Dh = self.n_out // H

        def split(w, heads):
            return (x @ w).reshape(B, T, heads, Dh)

        q = split(params["Wq"], H)
        k = split(params["Wk"], Hkv)
        v = split(params["Wv"], Hkv)
        if self.rope:
            positions = jnp.arange(T)
            q = rope_rotate(q, positions)
            k = rope_rotate(k, positions)

        def broadcast_kv(k, v):
            # GQA fallback for the H-wide attention cores (ring, flash,
            # dense): broadcast KV heads up to the query heads. The
            # banded kernel never needs this — it consumes the native
            # Hkv layout, which is where its decode-path HBM win lives.
            if Hkv != H:
                k = jnp.repeat(k, H // Hkv, axis=2)
                v = jnp.repeat(v, H // Hkv, axis=2)
            return k, v

        from deeplearning4j_tpu.parallel.ring_attention import (
            current_sequence_mesh,
        )

        seq_ctx = current_sequence_mesh()
        drop = (self.attn_dropout
                if train and self.attn_dropout and rng is not None else 0.0)
        if seq_ctx is not None and (drop or mask is not None
                                    or self.window is not None):
            # The user asked for sequence parallelism (usually because T
            # is too long for dense attention) but attention-dropout, a
            # padding mask, or a sliding window forces the dense path —
            # degrade loudly.
            import warnings

            why = ("attn_dropout" if drop
                   else "a sliding window" if self.window is not None
                   else "a padding mask")
            warnings.warn(
                f"sequence_parallel is active but {why} forces the dense "
                f"[T, T] attention path; the ring is bypassed for this "
                f"layer", stacklevel=2)
            seq_ctx = None
        if seq_ctx is not None:
            # sequence_parallel(mesh) context: T is sharded over the seq
            # axis; K/V ride the ring (parallel.ring_attention) so no
            # device holds the [T, T] scores. Padding masks and
            # attention-dropout keep the dense path.
            from deeplearning4j_tpu.parallel.ring_attention import (
                ring_self_attention,
            )

            k, v = broadcast_kv(k, v)
            o = ring_self_attention(q, k, v, seq_ctx.mesh,
                                    axis=seq_ctx.axis, causal=self.causal)
        elif self.window is not None and mask is None and not drop:
            # Sliding window (no mask/dropout): the banded kernel serves
            # this O(T·w) by grid construction, GQA-native. Banded-vs-
            # dense is the measured policy's call (kernel_defaults.
            # banded_policy; env hatch DL4J_TPU_ATTN=banded|dense).
            from deeplearning4j_tpu.ops.kernel_defaults import (
                banded_policy,
            )

            pol = banded_policy(T, H, Hkv, train=train)
            if pol.kind == "banded":
                from deeplearning4j_tpu.ops.banded_attention import (
                    banded_attention,
                )

                o = banded_attention(
                    q, k, v, self.window, self.causal, None, pol.block_q,
                    pol.block_k, jax.default_backend() != "tpu")
            else:
                k, v = broadcast_kv(k, v)
                o = self._masked_attention(q, k, v, None, self.causal,
                                           window=self.window)
        elif mask is not None or drop:
            # Padding mask and attention-weight dropout need the dense
            # path (dropout perturbs the post-softmax weights, which
            # never materialize inside the fused kernels).
            k, v = broadcast_kv(k, v)
            o = self._masked_attention(q, k, v, mask, self.causal,
                                       dropout=drop, rng=rng,
                                       window=self.window)
        else:
            k, v = broadcast_kv(k, v)
            # Flash-vs-dense, tile config, and backward selection all come
            # from the measured-winner policy (ops/kernel_defaults.py) —
            # the kernel must have a recorded hardware row beating XLA
            # dense at this mode/length, or dense memory pressure must
            # make the O(T) path mandatory. Env hatches: DL4J_TPU_ATTN*.
            from deeplearning4j_tpu.ops.kernel_defaults import (
                attention_policy,
            )

            pol = attention_policy(T, train=train)
            if pol.kind == "flash":
                from deeplearning4j_tpu.ops.attention import flash_attention

                o = flash_attention(q, k, v, self.causal, None,
                                    pol.block_q, pol.block_k, False,
                                    pol.backward)
            else:
                o = attention(q, k, v, causal=self.causal)
        o = o.reshape(B, T, self.n_out)
        y = o @ params["Wo"] + params["b"]
        return self._act(y), state

    @staticmethod
    def _masked_attention(q, k, v, mask, causal=False, dropout=0.0,
                          rng=None, window=None):
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d)
        bias = jnp.zeros((), s.dtype)
        if mask is not None:
            bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e30)
        if causal:
            t = s.shape[-1]
            band = jnp.tril(jnp.ones((t, t), jnp.bool_))
            bias = bias + jnp.where(band[None, None], 0.0, -1e30)
        if window is not None:
            # sliding window: `window` keys back (causal combines with
            # the tril above); bidirectional keeps |i-j| < window
            tq, tk = s.shape[-2], s.shape[-1]
            qi = jnp.arange(tq)[:, None]
            ki = jnp.arange(tk)[None, :]
            local = (ki > qi - window) if causal else (
                jnp.abs(qi - ki) < window)
            bias = bias + jnp.where(local[None, None], 0.0, -1e30)
        p = jax.nn.softmax(s + bias, axis=-1)
        if dropout:
            # Inverted dropout on the attention weights (the standard
            # attention-dropout placement, post-softmax pre-V).
            keep = 1.0 - dropout
            keep_mask = jax.random.bernoulli(rng, keep, p.shape)
            p = jnp.where(keep_mask, p / keep, 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@register_layer
@dataclasses.dataclass(frozen=True)
class PositionEmbeddingLayer(Layer):
    """Learned absolute position embedding added to [B, T, d] activations
    (extension: pairs with EmbeddingSequenceLayer for transformer inputs)."""

    CONSUMES = "rnn"   # [B, T, d] — shape-preserving sequence layer

    max_length: int = 512
    n_out: Optional[int] = None

    def infer_n_in(self, input_type: InputType):
        if self.n_out is None:
            return dataclasses.replace(self, n_out=input_type.size)
        return self

    def init_params(self, key, input_type, dtype=jnp.float32):
        d = self.n_out or input_type.size
        return {"P": 0.02 * jax.random.normal(
            key, (self.max_length, d), dtype)}, {}

    def decode_carry(self, batch: int, dtype=jnp.float32, *,
                     per_slot: bool = False, kv_dtype: str = None,
                     page_len: int = None, pages: int = None):
        # no KV here — kv_dtype/page geometry are accepted (and ignored)
        # so the session-carry builder can pass one policy to every
        # decode layer
        return {"pos": jnp.zeros((batch,) if per_slot else (), jnp.int32)}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None):
        t = x.shape[1]
        if t > self.max_length:
            raise ValueError(f"sequence length {t} > max_length "
                             f"{self.max_length}")
        if state is not None and "pos" in state:
            # decode stepping: positions continue from the carry offset
            pos = state["pos"]
            if getattr(pos, "ndim", 0) == 1:
                # per-slot vector positions (session decode): each row
                # gathers its own offsets; `mask` marks the valid prefix
                # of a padded chunk, which alone advances the position
                valid = None if mask is None else (mask > 0)
                n_new = (jnp.full(pos.shape, t, pos.dtype)
                         if valid is None
                         else valid.sum(axis=1).astype(pos.dtype))
                positions = pos[:, None] + jnp.arange(t)       # [B, t]
                p = jnp.take(params["P"],
                             jnp.minimum(positions, self.max_length - 1),
                             axis=0)                           # [B, t, d]
                # tracer-safe per-row overflow poison
                p = jnp.where((pos + n_new <= self.max_length)
                              [:, None, None], p, jnp.nan)
                return x + p, {"pos": pos + n_new}
            if (not isinstance(pos, jax.core.Tracer)
                    and int(pos) + t > self.max_length):
                raise ValueError(
                    f"decode position {int(pos)} + {t} > max_length "
                    f"{self.max_length}")
            p = jax.lax.dynamic_slice(
                params["P"], (pos, jnp.zeros((), pos.dtype)),
                (t, params["P"].shape[1]))
            # tracer-safe overflow poison (see MultiHeadAttention._decode)
            p = jnp.where(pos + t <= self.max_length, p, jnp.nan)
            return x + p[None], {"pos": pos + t}
        return x + params["P"][None, :t, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class TransformerEncoderBlock(Layer):
    """Pre-norm transformer block: x + MHA(norm(x)), then x + FFN(norm(x)).

    Modern extension (no reference counterpart — SURVEY §5 notes the
    reference predates attention). Composes the framework's own pieces:
    MultiHeadAttention (measured-policy attention core, ring attention
    under a seq mesh, GQA via num_kv_heads) and either a dense FFN or a
    MoEFeedForward (set n_experts > 0) for conditional compute.

    `norm="rms"` swaps LayerNorm for RMSNorm (no centering, no bias —
    one fewer reduction sweep per norm, the TPU-friendly modern choice)
    and `ffn_activation="swiglu"` swaps the GELU MLP for the gated
    SwiGLU variant; together with rope=True and num_kv_heads they make
    the block Llama-architecture-shaped.
    """

    CONSUMES = "rnn"   # [B, T, d] sequence activations

    n_in: Optional[int] = None
    num_heads: int = 4
    num_kv_heads: Optional[int] = None   # < num_heads -> GQA (see MHA)
    ffn_mult: int = 4
    causal: bool = True
    n_experts: int = 0            # 0 = dense FFN; >0 = MoE
    moe_k: int = 2
    max_cache: int = 1024         # KV-cache length for decode stepping
    rope: bool = False            # rotary position embedding on q/k
    norm: str = "layer"           # "layer" | "rms"
    ffn_activation: str = "gelu"  # "gelu" | "swiglu"
    window: Optional[int] = None  # sliding-window attention (see MHA)
    rolling_cache: bool = False   # ring-buffer decode cache (see MHA)

    def infer_n_in(self, input_type: InputType):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _sub(self):
        d = self.n_in
        attn = MultiHeadAttention(
            n_in=d, n_out=d, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, causal=self.causal,
            activation="identity", weight_init=self.weight_init,
            max_cache=self.max_cache, rope=self.rope, window=self.window,
            rolling_cache=self.rolling_cache)
        if self.n_experts > 0:
            from deeplearning4j_tpu.parallel.moe import MoEFeedForward

            ffn = MoEFeedForward(
                n_in=d, n_experts=self.n_experts, k=self.moe_k,
                hidden_mult=self.ffn_mult, activation="gelu",
                weight_init=self.weight_init, residual=False)
        else:
            ffn = None
        return attn, ffn

    def init_params(self, key, input_type, dtype=jnp.float32):
        d = self.n_in
        if self.norm not in ("layer", "rms"):
            raise ValueError(f"norm must be 'layer' or 'rms', "
                             f"got {self.norm!r}")
        if self.ffn_activation not in ("gelu", "swiglu"):
            raise ValueError(f"ffn_activation must be 'gelu' or 'swiglu', "
                             f"got {self.ffn_activation!r}")
        if self.ffn_activation == "swiglu" and self.n_experts > 0:
            raise ValueError(
                "ffn_activation='swiglu' applies to the dense FFN; with "
                "n_experts > 0 the MoE experts define their own "
                "activation (a silently-ignored config must not serde "
                "round-trip as if it trained SwiGLU)")
        ks = jax.random.split(key, 4)
        attn, moe = self._sub()
        params = {"ln1_g": jnp.ones((d,), dtype),
                  "ln2_g": jnp.ones((d,), dtype)}
        if self.norm == "layer":    # RMSNorm is bias-free
            params["ln1_b"] = jnp.zeros((d,), dtype)
            params["ln2_b"] = jnp.zeros((d,), dtype)
        ap, _ = attn.init_params(ks[0], input_type, dtype)
        params.update({f"attn_{k}": v for k, v in ap.items()})
        if moe is not None:
            mp, _ = moe.init_params(ks[1], input_type, dtype)
            params.update({f"moe_{k}": v for k, v in mp.items()})
        else:
            h = self.ffn_mult * d
            winit = self._winit()
            params.update({
                "ffn_w1": winit(ks[1], (d, h), dtype),
                "ffn_b1": jnp.zeros((h,), dtype),
                "ffn_w2": winit(ks[2], (h, d), dtype),
                "ffn_b2": jnp.zeros((d,), dtype),
            })
            if self.ffn_activation == "swiglu":
                # gated branch: silu(x W1) * (x W3) -> W2 (bias-free
                # gate matrix, the standard SwiGLU parameterization)
                params["ffn_w3"] = winit(ks[3], (d, h), dtype)
        return params, {}

    def _norm_apply(self, x, params, prefix):
        g = params[f"{prefix}_g"]
        if self.norm == "rms":
            # no centering, no bias: one reduction sweep instead of two
            ms = jnp.mean(x * x, axis=-1, keepdims=True)
            return x * jax.lax.rsqrt(ms + 1e-5) * g
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g \
            + params[f"{prefix}_b"]

    def decode_carry(self, batch: int, dtype=jnp.float32, *,
                     per_slot: bool = False, kv_dtype: str = None,
                     page_len: int = None, pages: int = None):
        attn, _ = self._sub()
        return {"attn": attn.decode_carry(batch, dtype, per_slot=per_slot,
                                          kv_dtype=kv_dtype,
                                          page_len=page_len, pages=pages)}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None):
        attn, moe = self._sub()
        ap = {k[5:]: v for k, v in params.items() if k.startswith("attn_")}
        h = self._norm_apply(x, params, "ln1")
        attn_carry = state.get("attn") if state else None
        a, a_st = attn.apply(ap, h, state=attn_carry, train=train, rng=rng,
                             mask=mask)
        x = x + a
        h = self._norm_apply(x, params, "ln2")
        new_state = {}
        if attn_carry is not None:
            new_state["attn"] = a_st
        if moe is not None:
            mp = {k[4:]: v for k, v in params.items() if k.startswith("moe_")}
            b_, t_, d_ = h.shape
            y, st = moe.apply(mp, h.reshape(b_ * t_, d_), state=None,
                              train=train, rng=rng)
            y = y.reshape(b_, t_, d_)
            if "aux_loss" in st:
                new_state["aux_loss"] = st["aux_loss"]
        elif self.ffn_activation == "swiglu":
            gate = jax.nn.silu(h @ params["ffn_w1"] + params["ffn_b1"])
            y = (gate * (h @ params["ffn_w3"])) @ params["ffn_w2"] \
                + params["ffn_b2"]
        else:
            y = jax.nn.gelu(h @ params["ffn_w1"] + params["ffn_b1"])
            y = y @ params["ffn_w2"] + params["ffn_b2"]
        y = self._maybe_dropout(y, train, rng)
        return x + y, new_state
