"""Global pooling (with mask support).

Reference parity: `nn/conf/layers/GlobalPoolingLayer.java` + impl
`nn/layers/pooling/GlobalPoolingLayer` — pools over time (RNN [B,T,F]) or
space (CNN NHWC) with MAX/AVG/SUM/PNORM, honoring per-timestep masks (the
reference's masking path for variable-length sequences).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_layer
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(Layer):
    pooling: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if x.ndim == 3:      # [B, T, F] — pool over time
            axes = (1,)
        elif x.ndim == 4:    # NHWC — pool over H, W
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects 3-D or 4-D input, got {x.shape}")

        p = self.pooling.lower()
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[..., None]  # [B, T, 1]
            if p == "max":
                x = jnp.where(m > 0, x, -jnp.inf)
                return jnp.max(x, axis=axes), state
            if p == "sum":
                return jnp.sum(x * m, axis=axes), state
            if p == "avg":
                s = jnp.sum(x * m, axis=axes)
                cnt = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
                return s / cnt, state
            if p == "pnorm":
                s = jnp.sum(jnp.abs(x * m) ** self.pnorm, axis=axes)
                return s ** (1.0 / self.pnorm), state

        if p == "max":
            return jnp.max(x, axis=axes), state
        if p == "sum":
            return jnp.sum(x, axis=axes), state
        if p == "avg":
            return jnp.mean(x, axis=axes), state
        if p == "pnorm":
            return jnp.sum(jnp.abs(x) ** self.pnorm, axis=axes) ** (1.0 / self.pnorm), state
        raise ValueError(f"Unknown pooling {self.pooling!r}")
