"""Feed-forward layers: Dense, Output, Loss, Activation, Dropout, Embedding,
AutoEncoder.

Reference parity: `nn/conf/layers/{DenseLayer,OutputLayer,LossLayer,
ActivationLayer,DropoutLayer,EmbeddingLayer,AutoEncoder}.java` + impls in
`nn/layers/feedforward/` and `nn/layers/BaseLayer.java` (preOutput = W·x+b at
`:384`). Parameter names follow the reference's DefaultParamInitializer
("W", "b"); kernels are stored [n_in, n_out] so the hot op is a single
batch-major matmul on the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, Params, State, register_layer
from deeplearning4j_tpu.nn.losses import LossFunction


@register_layer
@dataclasses.dataclass(frozen=True)
class DenseLayer(Layer):
    """Fully connected layer. Reference: `nn/conf/layers/DenseLayer.java`."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType) -> "DenseLayer":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32) -> Tuple[Params, State]:
        assert self.n_in and self.n_out, f"{self.name}: n_in/n_out unset"
        w = self._winit()(key, (self.n_in, self.n_out), dtype)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return params, {}

    def pre_output(self, params: Params, x):
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._maybe_dropout(x, train, rng)
        return self._act(self.pre_output(params, x)), state


@register_layer
@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """Dense + loss head. Reference: `nn/conf/layers/OutputLayer.java`
    (extends BaseOutputLayer); score computed in
    `MultiLayerNetwork.computeGradientAndScore()` (reference `:2082`)."""

    loss: Any = "mcxent"

    @property
    def is_output_layer(self) -> bool:
        return True

    def score(self, params: Params, x, labels, mask=None):
        """Mean per-example loss from the layer INPUT activations; the loss
        receives pre-activation output so fused stable forms apply."""
        preout = self.pre_output(params, x)
        return LossFunction.get(self.loss)(labels, preout, self.activation, mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class LossLayer(Layer):
    """Loss without params (activation + loss only). Reference:
    `nn/conf/layers/LossLayer.java`."""

    loss: Any = "mcxent"

    @property
    def is_output_layer(self) -> bool:
        return True

    def pre_output(self, params, x):
        return x

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self._act(x), state

    def score(self, params: Params, x, labels, mask=None):
        return LossFunction.get(self.loss)(labels, x, self.activation, mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """Parameterless activation. Reference: `nn/conf/layers/ActivationLayer.java`."""

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self._act(x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class PReLULayer(Layer):
    """Parametric ReLU with a learnable per-feature slope (reference:
    `nn/conf/layers/PReLULayer` precedent; Keras `PReLU` with
    shared_axes covering all but the last axis). alpha initializes to
    `alpha_init` (Keras default 0)."""

    n_out: Optional[int] = None
    alpha_init: float = 0.0

    def infer_n_in(self, input_type):
        if self.n_out is None:
            # alpha broadcasts over the trailing (feature/channel) axis
            n = (input_type.channels if input_type.kind in ("cnn", "cnn3d")
                 else input_type.size)
            return dataclasses.replace(self, n_out=n)
        return self

    def init_params(self, key, input_type, dtype=jnp.float32):
        n = self.n_out
        if n is None:
            n = (input_type.channels if input_type.kind in ("cnn", "cnn3d")
                 else input_type.size)
        return {"alpha": jnp.full((n,), self.alpha_init, dtype)}, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        a = params["alpha"]
        return jnp.where(x >= 0, x, a * x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Standalone dropout. Reference: `nn/conf/layers/DropoutLayer.java`."""

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self._maybe_dropout(x, train, rng), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(Layer):
    """Index → vector lookup, one index per example. Reference:
    `nn/conf/layers/EmbeddingLayer.java` (+ feedforward/embedding impl).
    On TPU the lookup is a gather (`jnp.take`), which XLA lowers natively —
    no one-hot matmul needed."""

    n_in: Optional[int] = None    # vocab size
    n_out: Optional[int] = None
    has_bias: bool = True

    def infer_n_in(self, input_type: InputType) -> "EmbeddingLayer":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        w = self._winit()(key, (self.n_in, self.n_out), dtype)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return params, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        idx = x
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        emb = jnp.take(params["W"], idx.astype(jnp.int32), axis=0)
        if self.has_bias:
            emb = emb + params["b"]
        return self._act(emb), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(Layer):
    """[batch, time] indices → [batch, time, n_out] vectors (modern
    counterpart of reference EmbeddingSequenceLayer)."""

    CONSUMES = "rnn"   # sequence input — no RnnToFeedForward before it

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {"W": self._winit()(key, (self.n_in, self.n_out), dtype)}, {}

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if x.ndim == 3 and x.shape[-1] == 1:
            x = x[..., 0]  # [B, T, 1] token-id tensors (InputType.recurrent(1))
        emb = jnp.take(params["W"], x.astype(jnp.int32), axis=0)
        return self._act(emb), state


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(Layer):
    """Denoising autoencoder, layerwise-pretrainable. Reference:
    `nn/conf/layers/AutoEncoder.java` + `nn/layers/feedforward/autoencoder/`.
    Supervised forward = encoder only (like the reference once pretrained);
    `reconstruction_score` drives unsupervised pretraining."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    corruption_level: float = 0.3
    loss: Any = "mse"

    @property
    def is_pretrainable(self) -> bool:
        return True

    def infer_n_in(self, input_type: InputType) -> "AutoEncoder":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {
            "W": self._winit()(k1, (self.n_in, self.n_out), dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),  # visible bias (decoder)
        }, {}

    def encode(self, params, x):
        return self._act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self._act(h @ params["W"].T + params["vb"])

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.encode(params, x), state

    def reconstruction_score(self, params, x, *, rng=None):
        corrupted = x
        if rng is not None and self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        recon = self.decode(params, self.encode(params, corrupted))
        return LossFunction.get(self.loss)(x, recon, "identity")
