"""ComputationGraph configuration: graph vertices + GraphBuilder DSL.

Reference parity: `nn/conf/ComputationGraphConfiguration.java` (748 LoC,
GraphBuilder), vertex configs in `nn/conf/graph/` (ElementWise, Merge,
Subset, Stack, Unstack, Scale, Shift, Reshape, L2, L2Normalize,
Preprocessor, LayerVertex + rnn/ LastTimeStep & duplicate-to-timeseries),
runtime vertices `nn/graph/vertex/impl/`.

The DAG is data: named vertices + input-name edges. Topological order is
computed once at build() (reference: `ComputationGraph.init():340,357`
computes `topologicalOrder`); the runtime just folds over that order, which
traces into one XLA computation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.preprocessors import Preprocessor
from deeplearning4j_tpu.utils.serde import register_serde, to_json, from_json


def resolve_output_type(name, vertex, in_types, n_inputs, known):
    """Shape propagation shared by GraphBuilder.build and
    ComputationGraph.init: when ALL input shapes are known, an
    output_type failure is a configuration error surfaced with the
    vertex name; partially-known inputs are skipped (downstream n_in
    must be explicit); zero-input vertices try best-effort."""
    if in_types and len(in_types) == n_inputs:
        try:
            known[name] = vertex.output_type(*in_types)
        except Exception as e:
            raise ValueError(
                f"vertex {name!r} ({type(vertex).__name__}): incompatible "
                f"with its input types {[str(t) for t in in_types]}: {e}"
            ) from e
    elif not n_inputs:
        try:
            known[name] = vertex.output_type(*in_types)
        except Exception:  # graft: allow(GL403): vertex stays untyped
            pass  # untyped zero-input vertex


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    """Base DAG node (non-layer). Pure like Layer: init_params/apply."""

    name: Optional[str] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def init_params(self, key, input_types: Sequence[InputType], dtype=jnp.float32):
        return {}, {}

    def apply(self, params, inputs: List, *, state=None, train=False,
              rng=None, mask=None):
        raise NotImplementedError


@register_serde
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """Add/Subtract/Product/Average/Max of same-shaped inputs.
    Reference: `nn/conf/graph/ElementWiseVertex.java` (validates input
    compatibility at config time like the reference's getOutputType)."""

    op: str = "add"

    def output_type(self, *input_types: InputType) -> InputType:
        def sig(t):
            # all shape-bearing fields; timesteps excluded (may be
            # legitimately unknown on one branch)
            return (t.kind, t.size, t.height, t.width, t.channels, t.depth)

        t0 = input_types[0]
        for t in input_types[1:]:
            if sig(t) != sig(t0):
                raise ValueError(
                    f"ElementWiseVertex inputs must have identical shapes; "
                    f"got {t0} vs {t}")
        return t0

    def apply(self, params, inputs, **kw):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op in ("sub", "subtract"):
            out = inputs[0] - inputs[1]
        elif op in ("mul", "product"):
            for x in inputs[1:]:
                out = out * x
        elif op in ("avg", "average"):
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown elementwise op {self.op!r}")
        return out, kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class PoolHelperVertex(GraphVertex):
    """Strip the first spatial row+column of a pooled CNN activation —
    the Caffe-import alignment shim. Reference:
    `nn/graph/vertex/impl/PoolHelperVertex.java:67-78` (interval(1, size)
    on the spatial dims; NCHW there, NHWC here)."""

    def output_type(self, *input_types: InputType) -> InputType:
        t = input_types[0]
        if t.kind != "cnn":
            raise ValueError(
                f"PoolHelperVertex needs a 4-D CNN (NHWC) input, got {t}")
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)

    def apply(self, params, inputs, **kw):
        return inputs[0][:, 1:, 1:, :], kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature (trailing) axis. Reference:
    `nn/conf/graph/MergeVertex.java` (channel axis for CNN — trailing in
    our NHWC layout, so one rule covers FF/RNN/CNN)."""

    def output_type(self, *input_types: InputType) -> InputType:
        t0 = input_types[0]
        if t0.kind == "ff":
            return InputType.feed_forward(sum(t.size for t in input_types))
        if t0.kind == "rnn":
            return InputType.recurrent(
                sum(t.size for t in input_types), t0.timesteps)
        if t0.kind == "cnn":
            return InputType.convolutional(
                t0.height, t0.width, sum(t.channels for t in input_types))
        return t0

    def apply(self, params, inputs, **kw):
        return jnp.concatenate(inputs, axis=-1), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive. Reference:
    `nn/conf/graph/SubsetVertex.java`."""

    from_: int = 0
    to: int = 0

    def output_type(self, *input_types: InputType) -> InputType:
        n = self.to - self.from_ + 1
        t0 = input_types[0]
        if t0.kind == "rnn":
            return InputType.recurrent(n, t0.timesteps)
        return InputType.feed_forward(n)

    def apply(self, params, inputs, **kw):
        return inputs[0][..., self.from_:self.to + 1], kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """Stack along the batch axis (examples concat). Reference:
    `nn/conf/graph/StackVertex.java`."""

    def apply(self, params, inputs, **kw):
        return jnp.concatenate(inputs, axis=0), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """Take slice `from_` of `stack_size` equal batch chunks. Reference:
    `nn/conf/graph/UnstackVertex.java`."""

    from_: int = 0
    stack_size: int = 1

    def apply(self, params, inputs, **kw):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_ * n:(self.from_ + 1) * n], kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    """Multiply by a fixed scalar. Reference: `nn/conf/graph/ScaleVertex.java`."""

    scale: float = 1.0

    def apply(self, params, inputs, **kw):
        return inputs[0] * self.scale, kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    """Add a fixed scalar. Reference: `nn/conf/graph/ShiftVertex.java`."""

    shift: float = 0.0

    def apply(self, params, inputs, **kw):
        return inputs[0] + self.shift, kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """Reshape to a fixed shape (batch dim preserved with -1 lead).
    Reference: `nn/conf/graph/ReshapeVertex.java`."""

    shape: Tuple[int, ...] = ()

    def apply(self, params, inputs, **kw):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape)), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over trailing axis. Reference: `nn/conf/graph/L2NormalizeVertex.java`."""

    eps: float = 1e-8

    def apply(self, params, inputs, **kw):
        x = inputs[0]
        n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + self.eps)
        return x / n, kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → [batch, 1]. Reference:
    `nn/conf/graph/L2Vertex.java` (used by siamese/triplet nets)."""

    eps: float = 1e-8

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(1)

    def apply(self, params, inputs, **kw):
        d = inputs[0] - inputs[1]
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """Wrap an InputPreProcessor as a vertex. Reference:
    `nn/conf/graph/PreprocessorVertex.java`."""

    preprocessor: Optional[Preprocessor] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return self.preprocessor.output_type(input_types[0])

    def apply(self, params, inputs, **kw):
        return self.preprocessor.apply(inputs[0]), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] → [B,F] last unmasked step. Reference:
    `nn/conf/graph/rnn/LastTimeStepVertex.java`."""

    mask_input: Optional[str] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(input_types[0].size)

    def apply(self, params, inputs, *, mask=None, **kw):
        x = inputs[0]
        if mask is None:
            return x[:, -1, :], kw.get("state")
        idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :], kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class CrossAttentionVertex(GraphVertex):
    """Cross-attention DAG node: queries from inputs[0], keys/values from
    inputs[1] — the encoder-decoder attention pattern. Modern extension
    (the RNN-era reference has no attention, SURVEY §5); non-causal by
    definition (the context is fully visible to every query). On TPU
    with 128-lane-tileable Tq/Tk of at least 512, the core runs the
    Pallas flash kernel (`ops/attention.py`, which supports Tq != Tk);
    otherwise XLA dense attention."""

    num_heads: int = 4
    n_out: Optional[int] = None
    # Name of the network input whose padding mask masks the KEYS (the
    # encoder stream). Without it, a mask is only applied when its length
    # unambiguously matches the context (Tk != Tq).
    key_mask_input: Optional[str] = None

    def output_type(self, *input_types: InputType) -> InputType:
        d = self.n_out or input_types[0].size
        return InputType.recurrent(d, input_types[0].timesteps)

    def init_params(self, key, input_types: Sequence[InputType],
                    dtype=jnp.float32):
        from deeplearning4j_tpu.nn.initializers import xavier

        d_q = input_types[0].size
        d_kv = input_types[1].size
        d = self.n_out or d_q
        if d % self.num_heads:
            raise ValueError(
                f"n_out {d} not divisible by num_heads {self.num_heads}")
        ks = jax.random.split(key, 4)
        return {
            "Wq": xavier(ks[0], (d_q, d), dtype),
            "Wk": xavier(ks[1], (d_kv, d), dtype),
            "Wv": xavier(ks[2], (d_kv, d), dtype),
            "Wo": xavier(ks[3], (d, d), dtype),
            "b": jnp.zeros((d,), dtype),
        }, {}

    def apply(self, params, inputs, *, state=None, train=False, rng=None,
              mask=None):
        x, ctx = inputs
        B, Tq, _ = x.shape
        Tk = ctx.shape[1]
        d = params["Wo"].shape[0]
        H = self.num_heads
        Dh = d // H
        q = (x @ params["Wq"]).reshape(B, Tq, H, Dh)
        k = (ctx @ params["Wk"]).reshape(B, Tk, H, Dh)
        v = (ctx @ params["Wv"]).reshape(B, Tk, H, Dh)
        key_mask = None
        if mask is not None:
            # A mask whose time axis matches the CONTEXT length masks the
            # keys (padded encoder positions must get zero weight). A
            # query-length mask carries no attention semantics here —
            # output positions are masked by the loss — and is ignored.
            # With key_mask_input configured, the graph runtime delivers
            # the named input's mask and it must match Tk; without it,
            # Tq == Tk is ambiguous and refused.
            if self.key_mask_input is not None:
                if mask.shape[1] != Tk:
                    raise ValueError(
                        f"key_mask_input mask length {mask.shape[1]} != "
                        f"context length {Tk}")
                key_mask = mask
            elif mask.shape[1] == Tk and Tq != Tk:
                key_mask = mask
            elif mask.shape[1] == Tk and Tq == Tk:
                raise ValueError(
                    "ambiguous mask (Tq == Tk): set key_mask_input to "
                    "the encoder input's name so the key mask is "
                    "delivered unambiguously")
            elif mask.shape[1] != Tq:
                raise ValueError(
                    f"mask time axis {mask.shape[1]} matches neither the "
                    f"query length {Tq} nor the context length {Tk}")
        from deeplearning4j_tpu.ops.kernel_defaults import attention_policy

        pol = attention_policy(Tq, Tk, train=train)
        if key_mask is None and pol.kind == "flash":
            from deeplearning4j_tpu.ops.attention import flash_attention

            o = flash_attention(q, k, v, False, None, pol.block_q,
                                pol.block_k, False, pol.backward)
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(Dh)
            if key_mask is not None:
                s = s + jnp.where(key_mask[:, None, None, :] > 0, 0.0,
                                  -1e30)
            o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
        y = o.reshape(B, Tq, d) @ params["Wo"] + params["b"]
        return y, state


@register_serde
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] → [B,T,F] broadcast over the timesteps of a reference input.
    Reference: `nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java`."""

    timesteps: int = 1

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType.recurrent(input_types[0].flat_size(), self.timesteps)

    def apply(self, params, inputs, **kw):
        x = inputs[0]
        return jnp.broadcast_to(
            x[:, None, :], (x.shape[0], self.timesteps, x.shape[-1])
        ), kw.get("state")


@register_serde
@dataclasses.dataclass(frozen=True)
class LayerVertex(GraphVertex):
    """A Layer as a DAG node (single input). Reference:
    `nn/conf/graph/LayerVertex.java`."""

    layer: Optional[Layer] = None
    preprocessor: Optional[Preprocessor] = None

    def output_type(self, *input_types: InputType) -> InputType:
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)

    def init_params(self, key, input_types, dtype=jnp.float32):
        # input type may be unknown (no set_input_types + upstream shape
        # not inferable, e.g. DL4J-imported configs with explicit nIn) —
        # layers with explicit dims don't need it
        it = input_types[0] if input_types else None
        if it is not None and self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.init_params(key, it, dtype)

    def apply(self, params, inputs, **kw):
        x = inputs[0]
        if self.preprocessor is not None:
            x = self.preprocessor.apply(x)
        return self.layer.apply(params, x, **kw)


@register_serde
@dataclasses.dataclass(frozen=True)
class ComputationGraphConfiguration:
    """Finalized DAG config. Reference:
    `nn/conf/ComputationGraphConfiguration.java`."""

    vertices: Dict[str, GraphVertex] = dataclasses.field(default_factory=dict)
    vertex_inputs: Dict[str, Tuple[str, ...]] = dataclasses.field(default_factory=dict)
    network_inputs: Tuple[str, ...] = ()
    network_outputs: Tuple[str, ...] = ()
    input_types: Dict[str, Any] = dataclasses.field(default_factory=dict)
    topological_order: Tuple[str, ...] = ()
    seed: int = 12345
    updater: Any = None
    dtype: str = "float32"
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    gradient_checkpointing: bool = False
    tbptt_fwd_length: int = 0
    tbptt_back_length: int = 0
    optimization_algo: str = "stochastic_gradient_descent"
    solver_iterations: int = 100

    def to_json(self) -> str:
        return to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        conf = from_json(s)
        return dataclasses.replace(
            conf,
            vertex_inputs={k: tuple(v) for k, v in conf.vertex_inputs.items()},
            network_inputs=tuple(conf.network_inputs),
            network_outputs=tuple(conf.network_outputs),
            topological_order=tuple(conf.topological_order),
        )


def toposort(vertex_inputs: Dict[str, Sequence[str]],
             network_inputs: Sequence[str]) -> List[str]:
    """Kahn topological order over vertex names. Reference:
    `ComputationGraph.topologicalSortOrder()` (`init():357`)."""
    indeg = {v: 0 for v in vertex_inputs}
    consumers: Dict[str, List[str]] = {}
    for v, ins in vertex_inputs.items():
        for i in ins:
            if i in vertex_inputs:
                indeg[v] += 1
                consumers.setdefault(i, []).append(v)
            elif i not in network_inputs:
                raise ValueError(f"Vertex {v!r} references unknown input {i!r}")
    ready = sorted([v for v, d in indeg.items() if d == 0])
    order = []
    while ready:
        v = ready.pop(0)
        order.append(v)
        for c in consumers.get(v, []):
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
    if len(order) != len(vertex_inputs):
        cyc = set(vertex_inputs) - set(order)
        raise ValueError(f"Graph has a cycle involving: {sorted(cyc)}")
    return order


class GraphBuilder:
    """Reference: `ComputationGraphConfiguration.GraphBuilder` reached via
    `NeuralNetConfiguration.Builder.graphBuilder()` (`:717`)."""

    def __init__(self, base):
        self._base = base
        self._vertices: Dict[str, GraphVertex] = {}
        self._inputs: Dict[str, Tuple[str, ...]] = {}
        self._network_inputs: List[str] = []
        self._network_outputs: List[str] = []
        self._input_types: Dict[str, InputType] = {}
        self._tbptt_fwd = 0
        self._tbptt_back = 0

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._network_inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        for name, t in zip(self._network_inputs, types):
            self._input_types[name] = t
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str,
                  preprocessor: Optional[Preprocessor] = None) -> "GraphBuilder":
        layer = dataclasses.replace(layer, name=name)
        self._vertices[name] = LayerVertex(
            name=name, layer=layer, preprocessor=preprocessor)
        self._inputs[name] = tuple(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = dataclasses.replace(vertex, name=name)
        self._inputs[name] = tuple(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._network_outputs = list(names)
        return self

    def tbptt(self, fwd: int, back: Optional[int] = None) -> "GraphBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_back = back if back is not None else fwd
        return self

    def build(self) -> ComputationGraphConfiguration:
        if (self._base._opt_algo != "stochastic_gradient_descent"
                and self._tbptt_fwd > 0):
            raise ValueError(
                "Truncated BPTT is only supported with "
                "stochastic_gradient_descent; full-batch solvers "
                f"({self._base._opt_algo}) cannot carry tBPTT state")
        defaults = self._base._defaults()
        order = toposort(self._inputs, self._network_inputs)

        # Shape inference + defaults cascade along topological order.
        known: Dict[str, InputType] = dict(self._input_types)
        finalized: Dict[str, GraphVertex] = {}
        for name in order:
            v = self._vertices[name]
            in_types = [known[i] for i in self._inputs[name] if i in known]
            if isinstance(v, LayerVertex):
                layer = v.layer.with_defaults(**defaults)
                if in_types:
                    it = in_types[0]
                    if v.preprocessor is not None:
                        it = v.preprocessor.output_type(it)
                    layer = layer.infer_n_in(it)
                from deeplearning4j_tpu.nn.config import _validate_layer
                _validate_layer(layer, -1)
                v = dataclasses.replace(v, layer=layer)
            finalized[name] = v
            resolve_output_type(name, v, in_types,
                                len(self._inputs[name]), known)
        missing = [o for o in self._network_outputs if o not in finalized]
        if missing:
            raise ValueError(f"set_outputs references unknown vertices: {missing}")

        return ComputationGraphConfiguration(
            vertices=finalized,
            vertex_inputs=dict(self._inputs),
            network_inputs=tuple(self._network_inputs),
            network_outputs=tuple(self._network_outputs),
            input_types=self._input_types,
            topological_order=tuple(order),
            seed=self._base._seed,
            updater=defaults["updater"],
            dtype=self._base._dtype,
            gradient_normalization=self._base._grad_norm,
            gradient_normalization_threshold=self._base._grad_norm_threshold,
            gradient_checkpointing=self._base._grad_ckpt,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            optimization_algo=self._base._opt_algo,
            solver_iterations=self._base._solver_iterations,
        )
