"""Network configuration DSL — config-as-data with a fluent builder.

Reference parity: `nn/conf/NeuralNetConfiguration.java:515` (Builder),
`.list():686` → `MultiLayerConfiguration`, `.graphBuilder():717` →
`ComputationGraphConfiguration`. Global defaults (activation, weightInit,
updater, l1/l2, dropout, seed — reference `:728-854`) cascade into every layer
config that didn't set its own, exactly as the reference clones the base conf
per layer. The built configuration is a frozen dataclass that JSON round-trips
(`to_json`/`from_json`), mirroring the reference's Jackson serde
(`MultiLayerConfiguration.toJson`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.preprocessors import Preprocessor, auto_preprocessor
from deeplearning4j_tpu.optim.updaters import Updater, resolve_updater, Sgd
from deeplearning4j_tpu.utils.serde import register_serde, to_json, from_json


class GradientNormalization:
    """Reference: `nn/conf/GradientNormalization.java` enum."""

    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENTWISE_ABSOLUTE_VALUE = "clip_elementwise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


@register_serde
@dataclasses.dataclass(frozen=True)
class MultiLayerConfiguration:
    """Finalized sequential-network config. Reference:
    `nn/conf/MultiLayerConfiguration.java`."""

    layers: Tuple[Layer, ...] = ()
    input_type: Optional[InputType] = None
    preprocessors: Dict[int, Preprocessor] = dataclasses.field(default_factory=dict)
    seed: int = 12345
    updater: Any = None
    dtype: str = "float32"
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    mini_batch: bool = True
    # remat every layer's activations in the backward pass — trades
    # ~33% more FLOPs for O(depth) less activation memory (the
    # jax.checkpoint lever for deep nets / long context; TPU-native
    # extension, no reference counterpart)
    gradient_checkpointing: bool = False
    tbptt_fwd_length: int = 0       # 0 = no truncated BPTT
    tbptt_back_length: int = 0
    backprop: bool = True
    pretrain: bool = False
    # Reference: OptimizationAlgorithm enum (`optimizationAlgo:746`) —
    # stochastic_gradient_descent | conjugate_gradient | lbfgs |
    # line_gradient_descent. Non-SGD algorithms run `solver_iterations`
    # full-batch solver steps per fit batch (optim/solvers.py).
    optimization_algo: str = "stochastic_gradient_descent"
    solver_iterations: int = 100

    def to_json(self) -> str:
        return to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        conf = from_json(s)
        # JSON dict keys are strings; restore int preprocessor indices.
        pp = {int(k): v for k, v in conf.preprocessors.items()}
        return dataclasses.replace(
            conf, layers=tuple(conf.layers), preprocessors=pp
        )

    def layer_names(self) -> List[str]:
        return [l.name for l in self.layers]


class NeuralNetConfiguration:
    """Entry point: `NeuralNetConfiguration.builder()` (reference `:515`)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    """Fluent builder holding global defaults; `.list(...)` produces a
    ListBuilder (reference `.list():686`), `.graph_builder()` a
    GraphBuilder (reference `.graphBuilder():717`)."""

    def __init__(self):
        self._seed = 12345
        self._activation: Optional[str] = None
        self._weight_init: Optional[str] = None
        self._updater: Any = None
        self._learning_rate: Any = None
        self._l1: Optional[float] = None
        self._l2: Optional[float] = None
        self._dropout: Optional[float] = None
        self._dtype: str = "float32"
        self._grad_norm: str = "none"
        self._grad_norm_threshold: float = 1.0
        self._mini_batch = True
        self._grad_ckpt = False
        self._opt_algo = "stochastic_gradient_descent"
        self._solver_iterations = 100

    # -- fluent setters (names mirror the reference builder methods) --
    def seed(self, s: int) -> "Builder":
        self._seed = int(s)
        return self

    def activation(self, a) -> "Builder":
        self._activation = a
        return self

    def weight_init(self, w) -> "Builder":
        self._weight_init = w
        return self

    def updater(self, u) -> "Builder":
        self._updater = resolve_updater(u)
        return self

    def learning_rate(self, lr) -> "Builder":
        self._learning_rate = lr
        return self

    def l1(self, v: float) -> "Builder":
        self._l1 = v
        return self

    def l2(self, v: float) -> "Builder":
        self._l2 = v
        return self

    def dropout(self, p: float) -> "Builder":
        self._dropout = p
        return self

    def dtype(self, d: str) -> "Builder":
        self._dtype = d
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0) -> "Builder":
        self._grad_norm = mode
        self._grad_norm_threshold = threshold
        return self

    def mini_batch(self, v: bool) -> "Builder":
        self._mini_batch = v
        return self

    def gradient_checkpointing(self, v: bool = True) -> "Builder":
        """Rematerialize layer activations in the backward pass
        (jax.checkpoint per layer/vertex) — memory for FLOPs."""
        self._grad_ckpt = v
        return self

    def optimization_algo(self, algo: str,
                          iterations: Optional[int] = None) -> "Builder":
        """Reference: `optimizationAlgo(OptimizationAlgorithm...)`:746.
        Accepts reference enum-style or snake_case names."""
        algo = str(algo).lower()
        aliases = {
            "sgd": "stochastic_gradient_descent",
            "cg": "conjugate_gradient",
        }
        algo = aliases.get(algo, algo)
        known = {"stochastic_gradient_descent", "conjugate_gradient",
                 "lbfgs", "line_gradient_descent"}
        if algo not in known:
            raise ValueError(
                f"Unknown optimization algorithm {algo!r}; known: "
                f"{sorted(known)}")
        self._opt_algo = algo
        if iterations is not None:
            self._solver_iterations = int(iterations)
        return self

    # -- terminals --
    def list(self, *layers: Layer) -> "ListBuilder":
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = tuple(layers[0])
        return ListBuilder(self, list(layers))

    def graph_builder(self):
        from deeplearning4j_tpu.nn.graph import GraphBuilder  # noqa: PLC0415

        return GraphBuilder(self)  # ComputationGraph DSL (nn/graph.py)

    # -- internals shared with graph builder --
    def _defaults(self) -> Dict[str, Any]:
        upd = self._updater
        if upd is None:
            upd = Sgd(self._learning_rate if self._learning_rate is not None else 1e-2)
        elif self._learning_rate is not None and hasattr(upd, "learning_rate"):
            upd = dataclasses.replace(upd, learning_rate=self._learning_rate)
        return dict(
            activation=self._activation,
            weight_init=self._weight_init or "xavier",
            updater=upd,
            l1=self._l1,
            l2=self._l2,
            dropout=self._dropout,
        )


class ListBuilder:
    """Reference: `NeuralNetConfiguration.ListBuilder` — collects layers,
    wires shapes/preprocessors, and builds a MultiLayerConfiguration."""

    def __init__(self, base: Builder, layers: List[Layer]):
        self._base = base
        self._layers = layers
        self._input_type: Optional[InputType] = None
        self._preprocessors: Dict[int, Preprocessor] = {}
        self._tbptt_fwd = 0
        self._tbptt_back = 0
        self._pretrain = False
        self._backprop = True

    def layer(self, l: Layer) -> "ListBuilder":
        self._layers.append(l)
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def input_preprocessor(self, idx: int, pp: Preprocessor) -> "ListBuilder":
        self._preprocessors[idx] = pp
        return self

    def tbptt(self, fwd_length: int, back_length: Optional[int] = None) -> "ListBuilder":
        """Truncated BPTT lengths (reference: `tBPTTForwardLength` etc.)."""
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length if back_length is not None else fwd_length
        return self

    def pretrain(self, v: bool) -> "ListBuilder":
        self._pretrain = v
        return self

    def backprop(self, v: bool) -> "ListBuilder":
        self._backprop = v
        return self

    def build(self) -> MultiLayerConfiguration:
        if (self._base._opt_algo != "stochastic_gradient_descent"
                and self._tbptt_fwd > 0):
            raise ValueError(
                "Truncated BPTT is only supported with "
                "stochastic_gradient_descent; full-batch solvers "
                f"({self._base._opt_algo}) cannot carry tBPTT state")
        defaults = self._base._defaults()
        layers: List[Layer] = []
        preprocessors = dict(self._preprocessors)
        cur = self._input_type

        for i, layer in enumerate(self._layers):
            layer = layer.with_defaults(**defaults)
            if layer.name is None:
                layer = dataclasses.replace(
                    layer, name=f"layer{i}_{type(layer).__name__.lower()}"
                )
            _validate_layer(layer, i)
            if cur is not None:
                # auto-insert preprocessor on family transitions
                if i not in preprocessors:
                    pp = auto_preprocessor(cur, _expected_kind(layer, cur))
                    if pp is not None:
                        preprocessors[i] = pp
                if i in preprocessors:
                    cur = preprocessors[i].output_type(cur)
                layer = layer.infer_n_in(cur)
                cur = layer.output_type(cur)
            else:
                # No input type declared: propagate from layers with explicit
                # dims (reference allows nIn-explicit configs without
                # setInputType).
                try:
                    cur = layer.output_type(cur)
                except Exception:
                    cur = None
            layers.append(layer)

        return MultiLayerConfiguration(
            layers=tuple(layers),
            input_type=self._input_type,
            preprocessors=preprocessors,
            seed=self._base._seed,
            updater=defaults["updater"],
            dtype=self._base._dtype,
            gradient_normalization=self._base._grad_norm,
            gradient_normalization_threshold=self._base._grad_norm_threshold,
            mini_batch=self._base._mini_batch,
            gradient_checkpointing=self._base._grad_ckpt,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            backprop=self._backprop,
            pretrain=self._pretrain,
            optimization_algo=self._base._opt_algo,
            solver_iterations=self._base._solver_iterations,
        )


def _validate_layer(layer: Layer, idx: int) -> None:
    """Fail fast at build() on unresolvable names (the reference validates
    in the builder too), instead of at first forward trace."""
    from deeplearning4j_tpu.nn.activations import Activation
    from deeplearning4j_tpu.nn.initializers import WeightInit
    from deeplearning4j_tpu.nn.losses import LossFunction

    try:
        Activation.get(layer.activation)
        WeightInit.get(layer.weight_init)
        if hasattr(layer, "loss"):
            LossFunction.get(layer.loss)
    except ValueError as e:
        raise ValueError(f"layer {idx} ({layer.name}): {e}") from None


def _expected_kind(layer: Layer, cur: InputType) -> str:
    """What input family does this layer consume? Drives preprocessor
    auto-insertion (reference: per-layer getPreProcessorForInputType)."""
    from deeplearning4j_tpu.nn.layers import convolution as conv_mod
    from deeplearning4j_tpu.nn.layers import recurrent as rnn_mod
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalization, LocalResponseNormalization,
    )
    from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer

    cnn_types = (
        conv_mod.ConvolutionLayer, conv_mod.SubsamplingLayer,
        conv_mod.ZeroPaddingLayer, conv_mod.Upsampling2DLayer,
        conv_mod.Cropping2DLayer, conv_mod.DepthwiseConvolution2DLayer,
        conv_mod.SeparableConvolution2DLayer,
    )
    from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention

    rnn_types = (
        rnn_mod.BaseRecurrentLayer, rnn_mod.Bidirectional,
        rnn_mod.GravesBidirectionalLSTM, rnn_mod.RnnOutputLayer,
        rnn_mod.LastTimeStep, conv_mod.Convolution1DLayer,
        conv_mod.Subsampling1DLayer, MultiHeadAttention,
    )
    # Layers that declare their input family explicitly ("any" = shape-
    # preserving, consume whatever arrives) bypass the type tables.
    declared = getattr(layer, "CONSUMES", None)
    if declared == "any":
        return cur.kind
    if declared is not None:
        return declared
    if isinstance(layer, cnn_types):
        return "cnn"
    if isinstance(layer, rnn_types):
        return "rnn"
    if isinstance(layer, (BatchNormalization, LocalResponseNormalization,
                          GlobalPoolingLayer)):
        return cur.kind  # shape-preserving: consume whatever arrives
    return "ff"
