"""Activation functions.

Reference parity: ND4J `IActivation` implementations as consumed by DL4J layer
configs (`nn/conf/NeuralNetConfiguration.java:781-795` sets a default
activation cascaded into every layer). The reference computes activations as
separate eager ops; here each is a pure jax function fused by XLA into the
surrounding matmul, so there is no separate "activation kernel" cost on TPU.

All functions take and return arrays of any shape and are differentiable via
`jax.grad` — the reference's hand-written `backprop(in, epsilon)` methods are
unnecessary under autodiff.
"""

from __future__ import annotations

from typing import Callable, Dict, Union

import jax
import jax.numpy as jnp


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha=alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x)


def silu(x):
    return jax.nn.silu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # Reference: ND4J ActivationRationalTanh — a cheap tanh approximation
    # 1.7159 * tanh_approx(2x/3) where tanh_approx clips via a rational poly.
    a = 0.6666667 * x
    abs_a = jnp.abs(a)
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + abs_a + a * a + 1.41645 * a**4))
    return 1.7159 * approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def cube(x):
    return x * x * x


def swish(x):
    return jax.nn.silu(x)


def mish(x):
    return jax.nn.mish(x)


def softmax(x):
    """Softmax over the trailing feature axis (class axis)."""
    return jax.nn.softmax(x, axis=-1)


def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def exp(x):
    return jnp.exp(x)


def clippedrelu(x, max_value: float = 6.0):
    return jnp.clip(x, 0.0, max_value)


def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


# Registry keyed by the lowercase names used in DL4J's `Activation` enum
# (reference: nd4j Activation enum referenced from NeuralNetConfiguration).
_REGISTRY: Dict[str, Callable] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "silu": silu,
    "swish": swish,
    "mish": mish,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "softmax": softmax,
    "logsoftmax": log_softmax,
    "thresholdedrelu": thresholdedrelu,
    "exp": exp,
    "clippedrelu": clippedrelu,
}


class Activation:
    """Enum-like accessor mirroring DL4J's `Activation` enum surface."""

    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SWISH = "swish"
    MISH = "mish"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    TANH = "tanh"
    HARDTANH = "hardtanh"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    CUBE = "cube"
    SOFTMAX = "softmax"
    LOGSOFTMAX = "logsoftmax"

    @staticmethod
    def get(name_or_fn: Union[str, Callable, None]) -> Callable:
        if name_or_fn is None:
            return identity
        if callable(name_or_fn):
            return name_or_fn
        key = str(name_or_fn).lower()
        if ":" in key:
            # Parametrized form "name:value" (e.g. "leakyrelu:0.2"), kept as a
            # plain string so layer configs stay JSON-serializable. Used by the
            # Keras importer for LeakyReLU/ELU/ThresholdedReLU alpha/theta.
            base, _, arg = key.partition(":")
            if base in _REGISTRY and arg:
                fn, val = _REGISTRY[base], float(arg)
                return lambda x: fn(x, val)
        if key not in _REGISTRY:
            raise ValueError(
                f"Unknown activation {name_or_fn!r}; known: {sorted(_REGISTRY)}"
            )
        return _REGISTRY[key]

    @staticmethod
    def register(name: str, fn: Callable) -> None:
        """Custom-activation plug-in seam (reference: custom IActivation tests)."""
        _REGISTRY[name.lower()] = fn

    @staticmethod
    def names():
        return sorted(_REGISTRY)


def resolve(name_or_fn) -> Callable:
    return Activation.get(name_or_fn)
