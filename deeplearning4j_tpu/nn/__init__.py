"""Neural-network core: configs, layers, activations, losses, initializers.

Reference parity: deeplearning4j-nn (`nn/conf`, `nn/layers`, `nn/weights`,
`nn/api`). Everything here is config-as-data (JSON-serializable dataclasses)
plus pure functions over pytrees — no mutable layer objects, so the whole
forward/backward compiles to a single XLA computation.
"""

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.losses import LossFunction
from deeplearning4j_tpu.nn.initializers import WeightInit

__all__ = ["InputType", "Activation", "LossFunction", "WeightInit"]
