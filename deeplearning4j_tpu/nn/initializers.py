"""Weight initialization schemes.

Reference parity: `nn/weights/WeightInit.java:47` (enum: XAVIER, RELU,
DISTRIBUTION, …) and `nn/weights/WeightInitUtil.java`. Fan-in/fan-out follow
the reference convention: for a dense kernel [n_in, n_out] fan_in = n_in;
for a conv kernel [kh, kw, c_in, c_out] (our NHWC/HWIO layout) fan_in =
kh*kw*c_in, fan_out = kh*kw*c_out.

All initializers are pure functions of an explicit `jax.random` key — the
reference's global `Nd4j.getRandom()` seed (`NeuralNetConfiguration.java:728`)
maps to the root PRNGKey threaded through model init.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = math.prod(shape[:-2])
    return receptive * shape[-2], receptive * shape[-1]


def zeros(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def normal(key, shape, dtype=jnp.float32):
    """Reference WeightInit.NORMAL: N(0, 1/sqrt(fan_in))."""
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fan_in, dtype))


def uniform(key, shape, dtype=jnp.float32):
    """Reference WeightInit.UNIFORM: U[-a, a], a = 1/sqrt(fan_in)."""
    fan_in, _ = _fans(shape)
    a = 1.0 / math.sqrt(max(fan_in, 1))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def xavier(key, shape, dtype=jnp.float32):
    """Reference WeightInit.XAVIER: N(0, 2/(fan_in+fan_out))."""
    fan_in, fan_out = _fans(shape)
    std = math.sqrt(2.0 / max(fan_in + fan_out, 1))
    return jax.random.normal(key, shape, dtype) * std


def xavier_uniform(key, shape, dtype=jnp.float32):
    """Reference WeightInit.XAVIER_UNIFORM: U[-a, a], a = sqrt(6/(fan_in+fan_out))."""
    fan_in, fan_out = _fans(shape)
    a = math.sqrt(6.0 / max(fan_in + fan_out, 1))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def xavier_fan_in(key, shape, dtype=jnp.float32):
    """Reference WeightInit.XAVIER_FAN_IN: N(0, 1/fan_in)."""
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(max(fan_in, 1), dtype))


def relu_init(key, shape, dtype=jnp.float32):
    """Reference WeightInit.RELU (He): N(0, 2/fan_in)."""
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / max(fan_in, 1))


def relu_uniform(key, shape, dtype=jnp.float32):
    """Reference WeightInit.RELU_UNIFORM: U[-a, a], a = sqrt(6/fan_in)."""
    fan_in, _ = _fans(shape)
    a = math.sqrt(6.0 / max(fan_in, 1))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def sigmoid_uniform(key, shape, dtype=jnp.float32):
    """Reference WeightInit.SIGMOID_UNIFORM: U[-a, a], a = 4*sqrt(6/(fan_in+fan_out))."""
    fan_in, fan_out = _fans(shape)
    a = 4.0 * math.sqrt(6.0 / max(fan_in + fan_out, 1))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def lecun_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / max(fan_in, 1))


def lecun_uniform(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    a = math.sqrt(3.0 / max(fan_in, 1))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


def identity_init(key, shape, dtype=jnp.float32):
    """Reference WeightInit.IDENTITY (square dense kernels only)."""
    if len(shape) == 2 and shape[0] == shape[1]:
        return jnp.eye(shape[0], dtype=dtype)
    raise ValueError(f"IDENTITY init needs a square 2-D shape, got {shape}")


def orthogonal(key, shape, dtype=jnp.float32, gain: float = 1.0):
    return jax.nn.initializers.orthogonal(scale=gain)(key, shape, dtype)


def distribution(dist: str = "normal", **kw) -> Callable:
    """Reference WeightInit.DISTRIBUTION + `nn/conf/distribution/*`.

    Supported: normal(mean,std), uniform(lower,upper), constant(value),
    truncated_normal(mean,std), lognormal(mean,std), binomial(n,p).
    """
    d = dist.lower()

    def init(key, shape, dtype=jnp.float32):
        if d == "normal" or d == "gaussian":
            return kw.get("mean", 0.0) + kw.get("std", 1.0) * jax.random.normal(key, shape, dtype)
        if d == "uniform":
            return jax.random.uniform(
                key, shape, dtype, minval=kw.get("lower", -1.0), maxval=kw.get("upper", 1.0)
            )
        if d == "constant":
            return jnp.full(shape, kw.get("value", 0.0), dtype)
        if d == "truncated_normal":
            return kw.get("mean", 0.0) + kw.get("std", 1.0) * jax.random.truncated_normal(
                key, -2.0, 2.0, shape, dtype
            )
        if d == "lognormal":
            return jnp.exp(
                kw.get("mean", 0.0) + kw.get("std", 1.0) * jax.random.normal(key, shape, dtype)
            )
        if d == "binomial":
            return jax.random.bernoulli(
                key, kw.get("p", 0.5), shape + (kw.get("n", 1),)
            ).sum(-1).astype(dtype)
        raise ValueError(f"Unknown distribution {dist!r}")

    init.__name__ = f"distribution_{d}"
    return init


_REGISTRY: Dict[str, Callable] = {
    "zero": zeros,
    "zeros": zeros,
    "ones": ones,
    "normal": normal,
    "uniform": uniform,
    "xavier": xavier,
    "xavier_uniform": xavier_uniform,
    "xavier_fan_in": xavier_fan_in,
    "relu": relu_init,
    "he": relu_init,
    "relu_uniform": relu_uniform,
    "sigmoid_uniform": sigmoid_uniform,
    "lecun_normal": lecun_normal,
    "lecun_uniform": lecun_uniform,
    "identity": identity_init,
    "orthogonal": orthogonal,
}


class WeightInit:
    """Enum-like accessor mirroring `nn/weights/WeightInit.java:47`."""

    ZERO = "zero"
    ONES = "ones"
    NORMAL = "normal"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    IDENTITY = "identity"
    ORTHOGONAL = "orthogonal"
    DISTRIBUTION = "distribution"

    @staticmethod
    def get(name_or_fn: Union[str, Callable, None]) -> Callable:
        if name_or_fn is None:
            return xavier
        if callable(name_or_fn):
            return name_or_fn
        key = str(name_or_fn).lower()
        if key not in _REGISTRY:
            raise ValueError(f"Unknown weight init {name_or_fn!r}; known: {sorted(_REGISTRY)}")
        return _REGISTRY[key]

    @staticmethod
    def register(name: str, fn: Callable) -> None:
        _REGISTRY[name.lower()] = fn


def resolve(name_or_fn) -> Callable:
    return WeightInit.get(name_or_fn)
