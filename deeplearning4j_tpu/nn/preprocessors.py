"""Input preprocessors — shape adapters between layer families.

Reference parity: `nn/conf/preprocessor/` (CnnToFeedForward, FeedForwardToCnn,
FeedForwardToRnn, RnnToFeedForward, RnnToCnn, CnnToRnn). The reference
auto-inserts these from `setInputType`; our builder does the same from
InputType transitions. All are pure reshapes that XLA folds into layout ops
(zero cost on TPU when shapes allow).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.utils.serde import register_serde


@dataclasses.dataclass(frozen=True)
class Preprocessor:
    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def apply(self, x, mask=None):
        raise NotImplementedError


@register_serde
@dataclasses.dataclass(frozen=True)
class CnnToFeedForward(Preprocessor):
    """NHWC → flat. Reference: CnnToFeedForwardPreProcessor."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())

    def apply(self, x, mask=None):
        return x.reshape(x.shape[0], -1)


@register_serde
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnn(Preprocessor):
    """Flat → NHWC. Reference: FeedForwardToCnnPreProcessor."""
    height: int = 0
    width: int = 0
    channels: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)

    def apply(self, x, mask=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)


@register_serde
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnn(Preprocessor):
    """[B,F] → [B,1,F] (or broadcast over known T). Reference:
    FeedForwardToRnnPreProcessor."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size(), 1)

    def apply(self, x, mask=None):
        return x[:, None, :]


@register_serde
@dataclasses.dataclass(frozen=True)
class RnnToFeedForward(Preprocessor):
    """[B,T,F] → [B*T? no — B,(T·F)]? The reference folds time into batch for
    time-distributed dense. Here RnnOutputLayer handles 3-D natively, so this
    preprocessor takes the LAST timestep for plain FF layers."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def apply(self, x, mask=None):
        return x[:, -1, :]


@register_serde
@dataclasses.dataclass(frozen=True)
class RnnToCnn(Preprocessor):
    """[B,T,F] with F=h·w·c → [B·T folded? No: [B,T,...] spatial per step].
    Simplified: collapse time into batch, reshape to NHWC (reference semantics
    for video-frame pipelines)."""
    height: int = 0
    width: int = 0
    channels: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)

    def apply(self, x, mask=None):
        b, t, f = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)


@register_serde
@dataclasses.dataclass(frozen=True)
class CnnToRnn(Preprocessor):
    """NHWC → [B, T=1, F]. Reference: CnnToRnnPreProcessor."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size(), 1)

    def apply(self, x, mask=None):
        return x.reshape(x.shape[0], 1, -1)


def auto_preprocessor(from_type: InputType, to_kind: str) -> Optional[Preprocessor]:
    """Pick the adapter for an InputType transition, as the reference's
    `getPreProcessorForInputType` does per layer config."""
    f = from_type.kind
    if f == to_kind or (f == "cnn_flat" and to_kind == "ff"):
        return None
    if f in ("cnn",) and to_kind == "ff":
        return CnnToFeedForward(from_type.height, from_type.width, from_type.channels)
    if f in ("ff", "cnn_flat") and to_kind == "cnn":
        if f == "cnn_flat":
            return FeedForwardToCnn(from_type.height, from_type.width, from_type.channels)
        raise ValueError(
            "Cannot infer CNN shape from a plain feed-forward input; use "
            "InputType.convolutional_flat(h, w, c)"
        )
    if f == "ff" and to_kind == "rnn":
        return FeedForwardToRnn()
    if f == "rnn" and to_kind == "ff":
        return RnnToFeedForward()
    if f == "cnn" and to_kind == "rnn":
        return CnnToRnn()
    return None
