"""Input type descriptors — static shape metadata for layer wiring.

Reference parity: `nn/conf/inputs/InputType.java` (feedForward, recurrent,
convolutional, convolutionalFlat) used by `setInputType` to auto-insert
preprocessors and infer nIn. Because XLA requires static shapes, InputType is
the single source of shape truth at configuration time.

TPU-first deviation from the reference: convolutional activations are NHWC
(channels-last) — the layout XLA/TPU prefers — instead of the reference's
NCHW. Recurrent activations are [batch, time, features] instead of the
reference's [batch, features, time].
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deeplearning4j_tpu.utils.serde import register_serde


@register_serde
@dataclasses.dataclass(frozen=True)
class InputType:
    """Shape descriptor, batch dimension excluded."""

    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat" | "cnn3d"
    size: Optional[int] = None          # ff / rnn feature size
    timesteps: Optional[int] = None     # rnn sequence length (None = variable at config time)
    height: Optional[int] = None
    width: Optional[int] = None
    channels: Optional[int] = None
    depth: Optional[int] = None         # cnn3d

    # ---- constructors (mirror InputType.feedForward(...) etc.) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="rnn", size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flattened image rows (e.g. raw MNIST 784-vectors); a preprocessor
        reshapes to NHWC before the first conv layer.
        Reference: InputType.convolutionalFlat."""
        return InputType(
            kind="cnn_flat", height=int(height), width=int(width), channels=int(channels)
        )

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(
            kind="cnn3d", depth=int(depth), height=int(height), width=int(width),
            channels=int(channels),
        )

    # ---- shape math ----
    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind == "rnn":
            return self.size
        if self.kind in ("cnn", "cnn_flat"):
            return self.height * self.width * self.channels
        if self.kind == "cnn3d":
            return self.depth * self.height * self.width * self.channels
        raise ValueError(self.kind)

    def shape(self, batch: int = 1) -> Tuple[int, ...]:
        """Concrete array shape including a batch dim (NHWC / BTF layouts)."""
        if self.kind == "ff":
            return (batch, self.size)
        if self.kind == "rnn":
            t = self.timesteps if self.timesteps is not None else 1
            return (batch, t, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnn_flat":
            return (batch, self.height * self.width * self.channels)
        if self.kind == "cnn3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    # ---- serde ----
    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)

    def __repr__(self):
        fields = ", ".join(
            f"{k}={v}" for k, v in dataclasses.asdict(self).items() if v is not None and k != "kind"
        )
        return f"InputType.{self.kind}({fields})"
