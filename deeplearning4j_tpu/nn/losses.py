"""Loss functions.

Reference parity: ND4J `ILossFunction` family as used by DL4J output layers
(`nn/conf/layers/OutputLayer`, score computed in
`MultiLayerNetwork.computeGradientAndScore()` — reference
`nn/multilayer/MultiLayerNetwork.java:2082`). The reference computes
`computeGradient(labels, preOutput, activationFn, mask)` by hand per loss; here
losses are pure functions of (labels, pre-activation output) and gradients come
from `jax.grad`, with numerically-stable fused paths for softmax+MCXENT and
sigmoid+XENT (the two hot classification cases, fused the way XLA wants).

Conventions
-----------
- ``preout`` is the PRE-activation output of the output layer ([batch, ...,
  n_out]); the loss applies the output activation itself so fused stable forms
  can be used. This mirrors the reference where ILossFunction receives
  preOutput + activationFn.
- ``mask`` is an optional per-example (or per-timestep) 0/1 array broadcastable
  to the per-example score shape; masked scores are excluded from the mean
  (reference: masking support threaded through every ILossFunction impl).
- Every loss returns a SCALAR mean-over-(unmasked)-examples score, matching
  `score()` semantics in the reference Model API (`nn/api/Model.java`).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import Activation

Array = jax.Array


def _reduce(per_example: Array, mask: Optional[Array]) -> Array:
    """Mean over examples, honoring a 0/1 mask (mask applies to score rows)."""
    if mask is None:
        return jnp.mean(per_example)
    mask = jnp.broadcast_to(mask, per_example.shape).astype(per_example.dtype)
    total = jnp.sum(per_example * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def _sum_features(x: Array) -> Array:
    """Sum the trailing feature axis → per-example score."""
    return jnp.sum(x, axis=-1)


def _apply_act(preout: Array, activation) -> Array:
    return Activation.get(activation)(preout)


def mcxent(labels, preout, activation="softmax", mask=None, weights=None):
    """Multi-class cross entropy; fused log-softmax when activation='softmax'.

    Reference: LossMCXENT. Supports soft labels and per-class `weights`.
    """
    if str(activation).lower() in ("softmax", "logsoftmax"):
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        p = _apply_act(preout, activation)
        logp = jnp.log(jnp.clip(p, 1e-10, 1.0))
    ll = labels * logp
    if weights is not None:
        ll = ll * jnp.asarray(weights, dtype=ll.dtype)
    return _reduce(-_sum_features(ll), mask)


def negativeloglikelihood(labels, preout, activation="softmax", mask=None, weights=None):
    """Reference: LossNegativeLogLikelihood — identical math to MCXENT in DL4J."""
    return mcxent(labels, preout, activation, mask, weights)


def sparse_mcxent(labels, preout, activation="softmax", mask=None):
    """Integer-label cross entropy (TPU-friendly: no one-hot materialization).

    No direct reference equivalent (DL4J one-hots everything); provided because
    on TPU gather-of-logsoftmax beats a one-hot matmul for large n_out.
    """
    logp = jax.nn.log_softmax(preout, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _reduce(-ll, mask)


def xent(labels, preout, activation="sigmoid", mask=None, weights=None):
    """Binary cross entropy; fused stable form when activation='sigmoid'.

    Reference: LossBinaryXENT.
    """
    if str(activation).lower() == "sigmoid":
        # log(sigmoid(x)) = -softplus(-x);  log(1-sigmoid(x)) = -softplus(x)
        per_feat = labels * jax.nn.softplus(-preout) + (1.0 - labels) * jax.nn.softplus(preout)
    else:
        p = jnp.clip(_apply_act(preout, activation), 1e-7, 1.0 - 1e-7)
        per_feat = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    if weights is not None:
        per_feat = per_feat * jnp.asarray(weights, dtype=per_feat.dtype)
    return _reduce(_sum_features(per_feat), mask)


def mse(labels, preout, activation="identity", mask=None, weights=None):
    """Reference: LossMSE (mean over features of squared error)."""
    out = _apply_act(preout, activation)
    d = (out - labels) ** 2
    if weights is not None:
        d = d * jnp.asarray(weights, dtype=d.dtype)
    return _reduce(jnp.mean(d, axis=-1), mask)


def l2(labels, preout, activation="identity", mask=None):
    """Reference: LossL2 (sum over features of squared error)."""
    out = _apply_act(preout, activation)
    return _reduce(_sum_features((out - labels) ** 2), mask)


def l1(labels, preout, activation="identity", mask=None):
    """Reference: LossL1 (sum of absolute error)."""
    out = _apply_act(preout, activation)
    return _reduce(_sum_features(jnp.abs(out - labels)), mask)


def mae(labels, preout, activation="identity", mask=None):
    """Reference: LossMAE (mean absolute error over features)."""
    out = _apply_act(preout, activation)
    return _reduce(jnp.mean(jnp.abs(out - labels), axis=-1), mask)


def mape(labels, preout, activation="identity", mask=None):
    """Reference: LossMAPE."""
    out = _apply_act(preout, activation)
    pct = jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), 1e-8)) * 100.0
    return _reduce(jnp.mean(pct, axis=-1), mask)


def msle(labels, preout, activation="identity", mask=None):
    """Reference: LossMSLE (mean squared log error)."""
    out = _apply_act(preout, activation)
    d = jnp.log1p(jnp.clip(out, 0.0)) - jnp.log1p(jnp.clip(labels, 0.0))
    return _reduce(jnp.mean(d * d, axis=-1), mask)


def hinge(labels, preout, activation="identity", mask=None):
    """Reference: LossHinge; labels in {-1, +1}."""
    out = _apply_act(preout, activation)
    return _reduce(_sum_features(jnp.maximum(0.0, 1.0 - labels * out)), mask)


def squared_hinge(labels, preout, activation="identity", mask=None):
    """Reference: LossSquaredHinge."""
    out = _apply_act(preout, activation)
    h = jnp.maximum(0.0, 1.0 - labels * out)
    return _reduce(_sum_features(h * h), mask)


def kl_divergence(labels, preout, activation="softmax", mask=None):
    """Reference: LossKLD — KL(labels || model)."""
    out = jnp.clip(_apply_act(preout, activation), 1e-10, 1.0)
    lab = jnp.clip(labels, 1e-10, 1.0)
    return _reduce(_sum_features(lab * (jnp.log(lab) - jnp.log(out))), mask)


def poisson(labels, preout, activation="identity", mask=None):
    """Reference: LossPoisson: sum(pred - label*log(pred))."""
    out = jnp.clip(_apply_act(preout, activation), 1e-10)
    return _reduce(_sum_features(out - labels * jnp.log(out)), mask)


def cosine_proximity(labels, preout, activation="identity", mask=None):
    """Reference: LossCosineProximity — negative cosine similarity."""
    out = _apply_act(preout, activation)
    dot = _sum_features(labels * out)
    norm = jnp.sqrt(_sum_features(labels * labels) * _sum_features(out * out) + 1e-12)
    return _reduce(-dot / norm, mask)


def wasserstein(labels, preout, activation="identity", mask=None):
    """Reference: LossWasserstein (critic loss: mean(label * pred))."""
    out = _apply_act(preout, activation)
    return _reduce(jnp.mean(labels * out, axis=-1), mask)


_REGISTRY: Dict[str, Callable] = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "sparse_mcxent": sparse_mcxent,
    "xent": xent,
    "binary_xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l1": l1,
    "l2": l2,
    "mae": mae,
    "mean_absolute_error": mae,
    "mape": mape,
    "mean_absolute_percentage_error": mape,
    "msle": msle,
    "mean_squared_logarithmic_error": msle,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "wasserstein": wasserstein,
}


class LossFunction:
    """Enum-like accessor mirroring DL4J's `LossFunctions.LossFunction`."""

    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    SPARSE_MCXENT = "sparse_mcxent"
    XENT = "xent"
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    MAPE = "mape"
    MSLE = "msle"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    WASSERSTEIN = "wasserstein"

    @staticmethod
    def get(name_or_fn: Union[str, Callable]) -> Callable:
        if callable(name_or_fn):
            return name_or_fn
        key = str(name_or_fn).lower()
        if key not in _REGISTRY:
            raise ValueError(f"Unknown loss {name_or_fn!r}; known: {sorted(_REGISTRY)}")
        return _REGISTRY[key]

    @staticmethod
    def register(name: str, fn: Callable) -> None:
        """Custom-loss plug-in seam (reference: custom ILossFunction tests)."""
        _REGISTRY[name.lower()] = fn

    @staticmethod
    def names():
        return sorted(_REGISTRY)


def resolve(name_or_fn) -> Callable:
    return LossFunction.get(name_or_fn)
