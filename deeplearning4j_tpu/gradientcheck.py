"""Numerical gradient checking — the correctness backbone.

Reference parity: `gradientcheck/GradientCheckUtil.java:48`
(`checkGradients`): central-difference numeric gradients over the FLAT param
vector vs analytic gradients, with per-parameter max relative error. The
reference runs this across 11 suites covering every layer/loss/masking combo
(SURVEY §4); our test suite mirrors that strategy.

Under autodiff the analytic gradient is `jax.grad` of the model loss; the
check validates that every layer's forward math is differentiable-correct
(catching e.g. wrong masking, non-differentiable kinks, state leakage).
Run in float64 on CPU for meaningful epsilon behavior.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.utils.pytrees import flatten_params

DEFAULT_EPS = 1e-5
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


def check_gradients(model, features, labels, *, features_mask=None,
                    labels_mask=None, eps: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    subset: Optional[int] = None, seed: int = 0,
                    print_results: bool = False) -> bool:
    """Central-difference check on a MultiLayerNetwork/ComputationGraph-like
    model exposing `_loss(params, states, features, labels, fmask, lmask,
    rng, train)` and `params_tree`/`state_tree`.

    `subset`: if set, check only this many randomly-chosen parameters
    (the reference checks all; subsetting keeps CI fast for big nets).
    """
    f64 = jnp.float64
    features = jnp.asarray(features, f64)
    labels = None if labels is None else jnp.asarray(labels, f64)
    fmask = None if features_mask is None else jnp.asarray(features_mask, f64)
    lmask = None if labels_mask is None else jnp.asarray(labels_mask, f64)

    params64 = jax.tree_util.tree_map(lambda a: jnp.asarray(a, f64),
                                      model.params_tree)
    states64 = jax.tree_util.tree_map(lambda a: jnp.asarray(a, f64),
                                      model.state_tree)
    flat, unravel = flatten_params(params64)

    def loss_flat(fv):
        loss, _ = model._loss(unravel(fv), states64, features, labels,
                              fmask, lmask, rng=None, train=False)
        return loss

    analytic = np.asarray(jax.grad(loss_flat)(flat), dtype=np.float64)
    flat_np = np.asarray(flat, dtype=np.float64)
    n = flat_np.shape[0]

    idxs = np.arange(n)
    if subset is not None and subset < n:
        idxs = np.random.default_rng(seed).choice(n, subset, replace=False)

    loss_jit = jax.jit(loss_flat)
    failures = []
    for i in idxs:
        orig = flat_np[i]
        fp = flat_np.copy()
        fp[i] = orig + eps
        fm = flat_np.copy()
        fm[i] = orig - eps
        numeric = (float(loss_jit(jnp.asarray(fp)))
                   - float(loss_jit(jnp.asarray(fm)))) / (2 * eps)
        a = analytic[i]
        abs_err = abs(a - numeric)
        denom = max(abs(a), abs(numeric))
        rel = abs_err / denom if denom > 0 else 0.0
        ok = rel < max_rel_error or abs_err < min_abs_error
        if not ok:
            failures.append((int(i), float(a), float(numeric), float(rel)))
        if print_results:
            print(f"param {i}: analytic={a:.8g} numeric={numeric:.8g} "
                  f"rel={rel:.3g} {'OK' if ok else 'FAIL'}")

    if failures:
        msg = "\n".join(
            f"  param {i}: analytic={a:.8g} numeric={nu:.8g} relError={r:.3g}"
            for i, a, nu, r in failures[:20]
        )
        print(f"Gradient check FAILED for {len(failures)}/{len(idxs)} params:\n{msg}")
    return not failures
