"""Early stopping: configuration, termination conditions, savers, trainer.

Reference parity: `earlystopping/EarlyStoppingConfiguration.java`,
`trainer/BaseEarlyStoppingTrainer.java:52-87`, `termination/` (8 conditions
incl. InvalidScoreIterationTerminationCondition = NaN guard,
MaxTimeIterationTerminationCondition), `saver/` (InMemory, LocalFile).
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, List, Optional

import numpy as np


# ---------------------------------------------------------------- conditions
class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    """Reference: termination/MaxEpochsTerminationCondition."""

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without improvement. Reference:
    termination/ScoreImprovementEpochTerminationCondition."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best = math.inf
        self._since = 0

    def terminate(self, epoch, score):
        if score < self._best - self.min_improvement:
            self._best = score
            self._since = 0
        else:
            self._since += 1
        return self._since > self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once score ≤ target. Reference: BestScoreEpochTerminationCondition."""

    def __init__(self, target: float):
        self.target = target

    def terminate(self, epoch, score):
        return score <= self.target


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Reference: termination/MaxTimeIterationTerminationCondition."""

    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def terminate(self, iteration, score):
        if self._start is None:
            self._start = time.monotonic()
        return (time.monotonic() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if score exceeds a bound (divergence guard). Reference:
    termination/MaxScoreIterationTerminationCondition."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, iteration, score):
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """NaN/Inf abort. Reference:
    termination/InvalidScoreIterationTerminationCondition (SURVEY §5 failure
    detection)."""

    def terminate(self, iteration, score):
        return not np.isfinite(score)


# ---------------------------------------------------------------- savers
class EarlyStoppingModelSaver:
    def save_best(self, net) -> None:
        raise NotImplementedError

    def save_latest(self, net) -> None:
        pass

    def get_best(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """Reference: saver/InMemoryModelSaver — deep-copies params."""

    def __init__(self):
        self._best_params = None
        self._best_state = None
        self._net = None

    def save_best(self, net):
        self._net = net
        self._best_params = net.params()
        import jax
        self._best_state = jax.tree_util.tree_map(
            lambda a: np.asarray(a), net.state_tree)

    def get_best(self):
        net = self._net.clone() if hasattr(self._net, "clone") else self._net
        net.set_params(self._best_params)
        import jax.numpy as jnp
        net.state_tree = {
            k: {kk: jnp.asarray(vv) for kk, vv in v.items()}
            if isinstance(v, dict) else v
            for k, v in self._best_state.items()
        }
        return net


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Reference: saver/LocalFileModelSaver — bestModel.zip / latestModel.zip."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def save_best(self, net):
        from deeplearning4j_tpu.models.serialize import save_model
        save_model(net, os.path.join(self.directory, "bestModel.zip"))

    def save_latest(self, net):
        from deeplearning4j_tpu.models.serialize import save_model
        save_model(net, os.path.join(self.directory, "latestModel.zip"))

    def get_best(self):
        from deeplearning4j_tpu.models.serialize import load_model
        return load_model(os.path.join(self.directory, "bestModel.zip"))


# ---------------------------------------------------------------- calculators
class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Held-out loss. Reference: scorecalc/DataSetLossCalculator."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            total += net.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if (self.average and n) else total


# ---------------------------------------------------------------- config
@dataclasses.dataclass
class EarlyStoppingConfiguration:
    """Reference: earlystopping/EarlyStoppingConfiguration (Builder)."""

    score_calculator: Optional[ScoreCalculator] = None
    model_saver: EarlyStoppingModelSaver = dataclasses.field(
        default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List[EpochTerminationCondition] = \
        dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    """Reference: earlystopping/EarlyStoppingResult."""

    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any


class EarlyStoppingTrainer:
    """Fit loop with termination/saving hooks. Reference:
    `trainer/BaseEarlyStoppingTrainer.java:52-87`."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.iterator = train_iterator

    def _fit_batch(self, ds) -> float:
        """One train step — the seam the parallel trainer overrides.
        Early stopping inspects the score every step (iteration
        termination conditions), so this is a per-step-visibility
        workload: materialize the deferred device loss here, at the
        consumption boundary."""
        return float(self.net._fit_batch(ds))

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        best_score = math.inf
        best_epoch = -1
        scores = {}
        epoch = 0
        reason, details = "MaxEpochs", "no termination condition fired"

        while True:
            terminated = False
            for ds in self.iterator:
                score = self._fit_batch(ds)
                self.net.iteration += 1
                for cond in cfg.iteration_termination_conditions:
                    if cond.terminate(self.net.iteration, score):
                        reason = "IterationTermination"
                        details = f"{type(cond).__name__} at iteration {self.net.iteration}"
                        terminated = True
                        break
                if terminated:
                    break
            if terminated:
                break

            if epoch % cfg.evaluate_every_n_epochs == 0:
                if cfg.score_calculator is not None:
                    s = cfg.score_calculator.calculate_score(self.net)
                else:
                    s = self.net.score_ if self.net.score_ is not None else math.inf
                scores[epoch] = s
                if s < best_score:
                    best_score = s
                    best_epoch = epoch
                    cfg.model_saver.save_best(self.net)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(self.net)
                fired = False
                for cond in cfg.epoch_termination_conditions:
                    if cond.terminate(epoch, s):
                        reason = "EpochTermination"
                        details = f"{type(cond).__name__} at epoch {epoch}"
                        fired = True
                        break
                if fired:
                    break
            epoch += 1

        if best_epoch < 0:  # never evaluated — save final state as best
            cfg.model_saver.save_best(self.net)
            best_epoch = epoch
            best_score = self.net.score_ or math.inf

        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch + 1,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            score_vs_epoch=scores,
            best_model=cfg.model_saver.get_best(),
        )


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over multi-device data-parallel training.

    Reference: `parallelism/EarlyStoppingParallelTrainer.java` (SURVEY
    §2.4) — early stopping wrapped around ParallelWrapper. Here each epoch
    batch runs through the sharded-jit step over the mesh (per-step ICI
    allreduce), with the same termination/saving hooks."""

    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, *, mesh=None, param_rules=None):
        super().__init__(config, net, train_iterator)
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

        self._pw = ParallelWrapper(net, mesh=mesh, param_rules=param_rules,
                                   prefetch_buffer=0)

    def _fit_batch(self, ds) -> float:
        score = float(self._pw._step(self._pw._pad_to_divisible(ds)))
        self.net.score_ = score
        return score
