"""Fault-injection harness: deterministic chaos for the recovery path.

ISSUE 6: "recovery is CI-testable rather than aspirational." Every
failure mode the preemption-proofing claims to survive gets an
injectable, CPU-deterministic trigger here, so tests/test_chaos_recovery
can kill runs at exact step/file boundaries and assert bit-identical
resume instead of hoping:

- `SigtermAtStep` — a TrainingListener that delivers a real SIGTERM (or
  degrades to `PreemptionHandler.request_stop()` off the main thread) at
  iteration N. CPython runs signal handlers between bytecodes on the
  main thread, so the flag is set before the next batch-boundary check —
  the stop lands at a deterministic batch.
- `CheckpointIOFault` — a `ShardedCheckpointer.fault_hook` that raises
  after a chosen number of file writes ("kill the writer after the first
  shard file"), proving the COMMIT protocol: a half-written step is
  invisible and resume picks the previous committed step.
- `FailingIterator` / `StallingIterator` — data-pipeline crash/stall at
  batch K (crash exercises flight-dump → restart → breadcrumb; stall
  exercises that slow input doesn't trip anything).
- scheduler-worker crashes are injected at the serving layer itself:
  `ContinuousBatchingScheduler.inject_worker_fault()` (the dispatch seam
  lives there), asserted through `ServingStats.worker_restarted`.

Everything here is test/ops tooling: no jax imports, no syncs, safe to
ship in production images (inert unless wired in).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from deeplearning4j_tpu.optim.listeners import TrainingListener

__all__ = [
    "SigtermAtStep", "CheckpointIOFault", "FailingIterator",
    "StallingIterator", "InjectedFault", "ReplicaKill",
]


class InjectedFault(OSError):
    """The exception every injector raises by default — recognizable in
    logs/flight dumps as chaos, never a real IO failure."""


class SigtermAtStep(TrainingListener):
    """Deliver SIGTERM to this process when iteration N completes.

    With a `handler` (a PreemptionHandler) the trigger calls
    `request_stop()` instead of `os.kill` — the off-main-thread path
    where signal delivery isn't available (threaded test runners).
    `fired` records delivery so tests can assert the fault actually ran.
    """

    def __init__(self, at_iteration: int,
                 handler: Optional[Any] = None):
        self.at_iteration = int(at_iteration)
        self.handler = handler
        self.fired = False

    def iteration_done(self, model, iteration, epoch, score):
        if self.fired or iteration < self.at_iteration:
            return
        self.fired = True
        if self.handler is not None:
            self.handler.request_stop()
        else:
            os.kill(os.getpid(), signal.SIGTERM)


class CheckpointIOFault:
    """`ShardedCheckpointer.fault_hook` raising at an exact file boundary.

    `fail_after=N` lets N matching writes succeed and kills the N+1-th;
    `kind` filters which boundary counts ("shard" | "manifest" |
    "commit" | None for all). `times` bounds how many checkpoints die
    (default 1: the writer fails once, later saves succeed — the
    recover-after-fault scenario). Counters are writer-thread-touched
    only, so no lock is needed beyond the GIL.
    """

    def __init__(self, *, fail_after: int = 1, kind: Optional[str] = "shard",
                 times: int = 1,
                 exc_factory: Callable[[], BaseException] = None):
        self.fail_after = int(fail_after)
        self.kind = kind
        self.times = int(times)
        self.exc_factory = exc_factory or (
            lambda: InjectedFault("injected checkpoint IO fault"))
        self.touched = 0
        self.raised = 0

    def __call__(self, kind: str, path: str) -> None:
        if self.kind is not None and kind != self.kind:
            return
        if self.raised >= self.times:
            return
        self.touched += 1
        if self.touched > self.fail_after:
            self.raised += 1
            self.touched = 0      # re-arm for the next checkpoint attempt
            raise self.exc_factory()


class ReplicaKill:
    """Kill a whole serving-fleet replica PROCESS — the fleet-scale
    fault: one mesh vanishes mid-stream with no goodbye (SIGKILL, so
    no handler runs and no socket closes cleanly). The router must
    notice the dead stream, fail the session over to another replica
    (KV handed off or re-prefilled), and the client's token sequence
    must continue uncorrupted — which the fleet chaos suite asserts
    byte-for-byte against an uninterrupted greedy run.

    `target` is a pid or any object with a `.pid` (a launcher
    ReplicaProcess, a subprocess.Popen). `after_tokens` arms a
    client-side trigger: call `maybe_fire(n_tokens_streamed)` from the
    consuming loop and the kill lands exactly once, at the first event
    at or past the threshold — deterministic in token count, not in
    wall time."""

    def __init__(self, target: Any, *, after_tokens: int = 0,
                 sig: int = signal.SIGKILL):
        self.target = target
        self.after_tokens = int(after_tokens)
        self.sig = sig
        self.fired = False

    @property
    def pid(self) -> int:
        return int(getattr(self.target, "pid", self.target))

    def fire(self) -> None:
        if self.fired:
            return
        self.fired = True
        try:
            os.kill(self.pid, self.sig)
        # graft: allow(GL403): target already dead — the fault still
        # "happened"; chaos injection is idempotent by design
        except ProcessLookupError:
            pass

    def maybe_fire(self, n_tokens: int) -> bool:
        if not self.fired and n_tokens >= self.after_tokens:
            self.fire()
            return True
        return False


class FailingIterator:
    """Iterable that raises at batch `fail_at` — the input-pipeline crash
    (a training exception, NOT a clean stop: the executor flight-dumps
    and re-raises, and the next run resumes from the last checkpoint).
    `times` bounds how many epochs/passes fail (default 1)."""

    def __init__(self, inner: Iterable, *, fail_at: int, times: int = 1,
                 exc_factory: Callable[[], BaseException] = None):
        self.inner = inner
        self.fail_at = int(fail_at)
        self.times = int(times)
        self.exc_factory = exc_factory or (
            lambda: InjectedFault("injected iterator failure"))
        self.raised = 0

    def __iter__(self) -> Iterator:
        for i, item in enumerate(iter(self.inner)):
            if i == self.fail_at and self.raised < self.times:
                self.raised += 1
                raise self.exc_factory()
            yield item


class StallingIterator:
    """Iterable that sleeps `stall_s` before yielding batch `stall_at` —
    a slow input pipeline. Recovery must treat this as ordinary ETL time
    (no watchdog trip, no spurious stop), which the chaos suite pins."""

    def __init__(self, inner: Iterable, *, stall_at: int,
                 stall_s: float = 0.25, times: int = 1):
        self.inner = inner
        self.stall_at = int(stall_at)
        self.stall_s = float(stall_s)
        self.times = int(times)
        self.stalled = 0

    def __iter__(self) -> Iterator:
        for i, item in enumerate(iter(self.inner)):
            if i == self.stall_at and self.stalled < self.times:
                self.stalled += 1
                time.sleep(self.stall_s)
            yield item
