"""ParallelInference — batched multi-device inference server.

Reference parity: `parallelism/ParallelInference.java:33-74` — modes
INPLACE/SEQUENTIAL/BATCHED with an observable queue batching concurrent
requests (`BatchedInferenceObservable`). Here: a host-side collector thread
coalesces requests up to `max_batch_size` (or `max_wait_ms`), pads to a
bucketed static shape (XLA needs static shapes; buckets avoid recompiles),
runs ONE sharded jit forward over the mesh's data axis, and scatters results
back to waiting futures.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import AXIS_DATA, make_mesh
from deeplearning4j_tpu.parallel.ring_attention import SeqCtxJitCache


class InferenceMode:
    """Reference: `ParallelInference.InferenceMode` (`:53`)."""

    INPLACE = "inplace"
    BATCHED = "batched"


class ParallelInference(SeqCtxJitCache):
    def __init__(self, net, *, mesh: Optional[Mesh] = None,
                 mode: str = InferenceMode.BATCHED,
                 max_batch_size: int = 64, max_wait_ms: float = 5.0,
                 batch_buckets: Optional[List[int]] = None):
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh()
        self.mode = mode
        self.max_batch = max_batch_size
        self.max_wait = max_wait_ms / 1e3
        self.buckets = sorted(set(batch_buckets or [1, 8, 32, max_batch_size]))
        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        # Drain accounting: every future enqueued on the collector is
        # counted until it completes (success OR failure) via its done
        # callback — single ownership, so the put-after-shutdown race and
        # the collector's exit drain can't double-count.
        self._pending = 0
        self._pending_cv = threading.Condition()
        from deeplearning4j_tpu.observe import get_registry

        reg = get_registry()
        self._m_dispatches = reg.counter("inference_dispatches_total")
        self._m_rows = reg.histogram("inference_batch_rows")
        self._worker: Optional[threading.Thread] = None
        if mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._collector, daemon=True)
            self._worker.start()

    # ---------------------------------------------------------- public
    def output(self, x) -> np.ndarray:
        """Blocking single request (thread-safe). Reference:
        `ParallelInference.output(INDArray)`."""
        x = np.asarray(x)
        if self.mode == InferenceMode.INPLACE:
            return self._run(x)
        if self._stop.is_set():
            raise RuntimeError("ParallelInference is shut down")
        fut: Future = Future()
        # Capture the caller's contextvars (e.g. an active
        # sequence_parallel context): the collector thread starts from an
        # empty Context, so tracing there would silently drop the swap.
        # The seq context itself is ALSO captured as the batching key —
        # the collector must never coalesce requests from different
        # contexts into one batch (the trace runs under the first
        # arrival's context, and another context's mesh can have
        # incompatible sharding-divisibility constraints).
        import contextvars

        from deeplearning4j_tpu.parallel.ring_attention import (
            current_sequence_mesh,
        )

        with self._pending_cv:
            self._pending += 1
        fut.add_done_callback(self._dec_pending)
        self._queue.put((x, fut, contextvars.copy_context(),
                         current_sequence_mesh()))
        # Close the put-after-drain race: if shutdown landed between the
        # check above and the put, the collector's exit drain may already
        # have run and this item would hang forever. The collector's
        # completions are done-guarded, so failing here is safe either way.
        if self._stop.is_set() and not fut.done():
            try:
                fut.set_exception(RuntimeError(
                    "ParallelInference is shut down"))
            except Exception:  # graft: allow(GL403): benign lost race
                pass   # collector won the race and completed it
        return fut.result()

    def run_batch(self, x) -> np.ndarray:
        """Scheduler SPI: run one already-formed batch synchronously on
        the device — bucketed pad + per-bucket jit cache + oversize
        chunking — bypassing the collector queue. This is the data-plane
        hook the serving control plane's continuous-batching scheduler
        dispatches through."""
        return self._run(np.asarray(x))

    def warmup(self, feat_shape, dtype=np.float32) -> int:
        """Compile (and execute once) the forward for every batch bucket.

        Deploy-time warm: the serving registry calls this before flipping
        traffic to a new model version so no live request ever pays the
        trace+compile. Returns the number of buckets warmed."""
        for b in self.buckets:
            self._run(np.zeros((b, *tuple(feat_shape)), dtype))
        return len(self.buckets)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Scheduler SPI drain hook: block until every enqueued request
        has completed (successfully or with an error). Returns False on
        timeout. Does NOT stop the collector — callers that want to stop
        serving use shutdown(), which fails leftovers explicitly."""
        with self._pending_cv:
            return self._pending_cv.wait_for(
                lambda: self._pending == 0, timeout)

    def shutdown(self):
        self._stop.set()
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join(timeout=2)

    # --------------------------------------------------------- internal
    def _dec_pending(self, _fut):
        with self._pending_cv:
            self._pending -= 1
            self._pending_cv.notify_all()
    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _forward_jit(self, padded_batch: int, feat_shape):
        key = (padded_batch, feat_shape)
        if key not in self._jit_cache:
            net = self.net
            sharding = NamedSharding(
                self.mesh,
                P(AXIS_DATA, *([None] * len(feat_shape))))

            def fwd(params, states, x):
                y, _, _, _ = net._forward(params, states, x,
                                          train=False, rng=None)
                return y

            # graft: allow(GL301): benign double-compile race — the dict
            # write is atomic under the GIL and both values are equivalent
            self._jit_cache[key] = jax.jit(fwd, in_shardings=(None, None, sharding))
        return self._jit_cache[key]

    def _run(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        cap = self.buckets[-1]
        if n > cap:
            # Oversized request: running it whole would key the jit cache
            # on an unbucketed shape (one compile per distinct n) and can
            # hand the sharded data axis an indivisible batch. Chunk to
            # the largest bucket and reassemble in order.
            return np.concatenate(
                [self._run(x[i:i + cap]) for i in range(0, n, cap)], axis=0)
        # one device dispatch (chunked oversize requests count per chunk)
        self._m_dispatches.inc()
        self._m_rows.observe(n)
        b = self._bucket(n)
        # data-axis divisibility for sharding
        d = self.mesh.shape[AXIS_DATA]
        b = ((b + d - 1) // d) * d
        if n < b:
            pad = np.repeat(x[:1], b - n, axis=0)
            x = np.concatenate([x, pad], axis=0)
        fn = self._forward_jit(b, x.shape[1:])
        y = fn(self.net.params_tree, self.net.state_tree,
               jnp.asarray(x, self.net.dtype))
        return np.asarray(y)[:n]

    def _collector(self):
        """Coalesce concurrent requests into one device batch.
        Reference: BatchedInferenceObservable + ObservablesProvider.

        Requests are grouped by their captured sequence_parallel context:
        a batch only ever contains requests that share one context, so
        the single trace (run under that context) is correct for every
        member. A request from a different context ends the current
        batch and seeds the next one."""
        held = None
        while not self._stop.is_set():
            if held is not None:
                item, held = held, None
            else:
                try:
                    item = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            if item is None:
                break
            batch = [item]
            seq_key = item[3]
            total = item[0].shape[0]
            deadline = self.max_wait
            import time
            t0 = time.monotonic()
            while total < self.max_batch:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._stop.set()
                    break
                if nxt[3] != seq_key:
                    held = nxt       # different context: next batch's seed
                    break
                batch.append(nxt)
                total += nxt[0].shape[0]
            xs = np.concatenate([b[0] for b in batch], axis=0)
            try:
                ys = batch[0][2].run(self._run, xs)
                off = 0
                for x, fut, _ctx, _key in batch:
                    if not fut.done():   # output() may have failed it
                        fut.set_result(ys[off:off + x.shape[0]])
                    off += x.shape[0]
            except BaseException as e:
                for _x, fut, _ctx, _key in batch:
                    if not fut.done():
                        fut.set_exception(e)
        # Drain on exit: a parked next-batch seed (`held`) or requests
        # still queued at shutdown must fail loudly — a silently dropped
        # Future would block its caller in fut.result() forever.
        leftovers = [held] if held is not None else []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for item in leftovers:
            if item is None:
                continue
            fut = item[1]
            if not fut.done():
                fut.set_exception(RuntimeError(
                    "ParallelInference shut down before serving this "
                    "request"))
