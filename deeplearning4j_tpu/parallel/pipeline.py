"""Pipeline parallelism — GPipe-style microbatch schedule over the `pipe`
mesh axis.

No reference counterpart: DL4J implements only data parallelism (SURVEY
§2.4 enumerates all five flavors); pipeline parallelism is one of the
green-field TPU-scale extensions demanded by SURVEY §7 step 7.

TPU-first design:
- Stages are STACKED: every stage has an identical parameter pytree and the
  per-stage leaves are stacked on a leading axis that is sharded over the
  `pipe` mesh axis. Each device therefore holds exactly its stage's weights
  (transformer-block style; heterogeneous prologue/epilogue layers live
  outside the pipelined trunk).
- The schedule is a single `lax.scan` over ticks inside `shard_map`;
  activations move stage→stage via `lax.ppermute` — a point-to-point ICI
  transfer, not a broadcast. With B microbatches and S stages, the scan runs
  B + S - 1 ticks (the classic GPipe fill+drain bubble).
- Backward is *derived*: `jax.grad` through scan + ppermute yields the
  reverse pipeline schedule automatically (ppermute's transpose is the
  reverse permutation) — no hand-written backward pass.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.observe import donatemon
from deeplearning4j_tpu.parallel.mesh import (AXIS_DATA, AXIS_PIPE,
                                              shard_map_compat)

_tmap = jax.tree_util.tree_map


def _pcast_varying(x, axis: str):
    """Mark `x` device-varying over `axis` (jax 0.9 vma typing). Older
    jax has no `lax.pcast` (nor vma tracking at all), so identity is the
    correct degradation — there is no varying/unvarying distinction to
    violate there."""
    try:
        return lax.pcast(x, (axis,), to="varying")
    # graft: allow(GL403): version probe — AttributeError = pre-vma jax,
    # ValueError = vma tracking off in this trace; both mean "no cast"
    except (AttributeError, ValueError):
        return x


def stack_stage_params(stage_params: Sequence[Any]):
    """Stack S structurally-identical per-stage pytrees on a new leading
    axis (the axis that gets sharded over `pipe`)."""
    return _tmap(lambda *xs: jnp.stack(xs), *stage_params)


def unstack_stage_params(stacked) -> List[Any]:
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    n = leaves[0].shape[0]
    return [
        jax.tree_util.tree_unflatten(treedef, [l[i] for l in leaves])
        for i in range(n)
    ]


def stage_sharding(stacked, mesh: Mesh, axis: str = AXIS_PIPE):
    """NamedShardings placing stage i's slice on pipe-coordinate i."""
    return _tmap(lambda _: NamedSharding(mesh, P(axis)), stacked)


def split_microbatches(x, n_micro: int):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def merge_microbatches(y):
    return y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])


def make_pipeline_fn(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     n_stages: int, n_micro: int, mesh: Mesh, *,
                     axis: str = AXIS_PIPE,
                     data_axis: Optional[str] = None):
    """Build f(stacked_params, x_mb) -> y_mb running the GPipe schedule.

    stage_fn: (one stage's params, activations [mb, ...]) -> [mb, ...];
      activation shape must be stage-invariant (uniform-trunk restriction).
    x_mb / y_mb: [n_micro, mb, ...]. If `data_axis` is given, the per-
      microbatch batch dim is additionally sharded over it (2-D pipe×data).
    """
    if n_stages < 1:
        raise ValueError("n_stages must be >= 1")
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    total_ticks = n_micro + n_stages - 1

    def local_fn(params_shard, x_mb):
        my_params = _tmap(lambda p: p[0], params_shard)
        stage = lax.axis_index(axis)

        def tick(buf, t):
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            feed = lax.dynamic_index_in_dim(x_mb, mb_idx, keepdims=False)
            inp = jnp.where(stage == 0, feed, buf)
            out = stage_fn(my_params, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        # Mark the carry as device-varying over `pipe` (jax 0.9 vma typing:
        # the ppermute output is varying, so the initial carry must be too).
        buf0 = _pcast_varying(jnp.zeros_like(x_mb[0]), axis)
        _, outs = lax.scan(tick, buf0, jnp.arange(total_ticks))
        # Last stage's outputs for microbatch m appear at tick m + S - 1.
        tail = lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro, axis=0)
        mask = (stage == n_stages - 1).astype(tail.dtype)
        return lax.psum(tail * mask, axis)

    in_x = P(None, data_axis) if data_axis else P()
    out_y = P(None, data_axis) if data_axis else P()
    return shard_map_compat(local_fn, mesh, (P(axis), in_x), out_y,
                            check=True)


def make_pipeline_1f1b_fn(stage_fn: Callable[[Any, jax.Array], jax.Array],
                          last_loss: Callable[[Any, jax.Array, jax.Array],
                                              jax.Array],
                          n_stages: int, n_micro: int, mesh: Mesh, *,
                          axis: str = AXIS_PIPE):
    """1F1B (eager-backward) pipeline schedule with hand-rolled backward.

    Unlike the GPipe path (`make_pipeline_fn` + jax.grad, which stores
    residuals for ALL B microbatches before any backward runs), this
    schedule starts each microbatch's backward as soon as its forward
    reaches the last stage, interleaving one forward and one backward per
    tick. Activation memory is the 1F1B bound: a circular input stash of
    depth min(B, 2S-1) — O(stages), independent of microbatch count — with
    per-stage recompute (rematerialization) in the backward.

    stage_fn: (stage params, activations [mb, ...]) -> [mb, ...]
    last_loss: (epilogue params, trunk output [mb, ...], labels[mb, ...])
      -> scalar mean loss for the microbatch; runs ON the last stage, so
      its backward seeds the reverse pipeline the same tick the forward
      finishes — that simultaneity is what makes the schedule 1F1B.

    Returns f(stacked_params, epi_params, x_mb, labels_mb) ->
      (mean_loss, trunk_grads [stacked, P(pipe)], epi_grads [replicated],
       dx_mb [dL/d trunk-input per microbatch, replicated])
    — everything needed to chain a prologue's vjp and an updater behind it.
    """
    S, B = n_stages, n_micro
    T = B + 2 * (S - 1)
    D = max(1, min(B, 2 * S - 1))     # stash depth: the 1F1B memory bound
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def local_fn(params_shard, epi_params, x_mb, y_mb):
        my = _tmap(lambda p: p[0], params_shard)
        stage = lax.axis_index(axis)
        is_first = (stage == 0)
        is_last = (stage == S - 1)

        def var(x):    # noqa: E306 — defined before first use below
            return _pcast_varying(x, axis)

        # The epilogue params arrive replicated (unvarying over `pipe`).
        # vjp wrt an UNVARYING input of a varying computation inserts an
        # implicit cross-device psum in the cotangent — which would fold the
        # other stages' (masked-out) garbage losses into d_epi. Cast to
        # varying so each stage gets ITS OWN cotangent; the explicit
        # mask + psum below does the real aggregation.
        epi_params = _tmap(var, epi_params)


        carry0 = (
            var(jnp.zeros_like(x_mb[0])),                 # fwd in-buffer
            var(jnp.zeros_like(x_mb[0])),                 # bwd in-buffer
            var(jnp.zeros((D,) + x_mb.shape[1:], x_mb.dtype)),  # input stash
            _tmap(lambda p: var(jnp.zeros_like(p)), my),  # trunk grad accum
            _tmap(lambda p: var(jnp.zeros_like(p)), epi_params),
            var(jnp.zeros_like(x_mb)),                    # dL/dx per mb
            var(jnp.zeros((), jnp.float32)),              # loss accum
        )

        def tick(carry, t):
            fwd_buf, bwd_buf, stash, gacc, epi_g, dx_all, loss_sum = carry

            # ---------------- forward half ----------------
            m_f = t - stage
            act_f = jnp.logical_and(m_f >= 0, m_f < B)
            m_f_c = jnp.clip(m_f, 0, B - 1)
            feed = lax.dynamic_index_in_dim(x_mb, m_f_c, keepdims=False)
            x_in = jnp.where(is_first, feed, fwd_buf)
            y = stage_fn(my, x_in)
            stash = jnp.where(
                act_f,
                lax.dynamic_update_index_in_dim(stash, x_in, m_f_c % D, 0),
                stash)
            fwd_next = lax.ppermute(y, axis, fwd_perm)

            # ------------- last-stage loss + seed -------------
            # Guarded by lax.cond so only the last stage pays for the
            # epilogue forward+vjp (for a transformer that's the vocab
            # projection — the heaviest per-token op); the other S-1
            # stages take the zeros branch.
            label = lax.dynamic_index_in_dim(y_mb, m_f_c, keepdims=False)
            on_last = jnp.logical_and(act_f, is_last)

            def do_loss(yy):
                loss_val, loss_vjp = jax.vjp(
                    lambda ep, y2: last_loss(ep, y2, label), epi_params, yy)
                d_ep, dy = loss_vjp(var(jnp.ones((), loss_val.dtype)))
                return loss_val.astype(jnp.float32), d_ep, dy

            def no_loss(yy):
                return (var(jnp.zeros((), jnp.float32)),
                        _tmap(lambda p: var(jnp.zeros_like(p)), epi_params),
                        jnp.zeros_like(yy))

            loss_val, d_epi, dldy = lax.cond(on_last, do_loss, no_loss, y)
            loss_sum = loss_sum + loss_val
            epi_g = _tmap(lambda a, g: a + g, epi_g, d_epi)

            # ---------------- backward half ----------------
            # Stage s runs mb m's backward at tick m + 2(S-1) - s; for the
            # last stage that's the SAME tick as its forward, so dldy above
            # is this tick's gy — backward starts with zero delay (1F1B).
            m_b = t - 2 * (S - 1) + stage
            act_b = jnp.logical_and(m_b >= 0, m_b < B)
            m_b_c = jnp.clip(m_b, 0, B - 1)
            x_saved = stash[m_b_c % D]
            gy = jnp.where(is_last, dldy, bwd_buf)
            _, svjp = jax.vjp(lambda p, xx: stage_fn(p, xx), my, x_saved)
            gp, gx = svjp(gy)
            w_b = act_b.astype(jnp.float32)
            gacc = _tmap(lambda a, g: a + g * w_b.astype(a.dtype), gacc, gp)
            dx_all = jnp.where(
                jnp.logical_and(act_b, is_first),
                lax.dynamic_update_index_in_dim(dx_all, gx, m_b_c, 0),
                dx_all)
            bwd_next = lax.ppermute(gx, axis, bwd_perm)

            return (fwd_next, bwd_next, stash, gacc, epi_g, dx_all,
                    loss_sum), None

        (_, _, _, gacc, epi_g, dx_all, loss_sum), _ = lax.scan(
            tick, carry0, jnp.arange(T))

        # loss/epilogue grads live on the last stage, dx on the first:
        # psum replicates them (other stages contribute zeros).
        loss_mean = lax.psum(loss_sum, axis) / B
        epi_g = _tmap(lambda g: lax.psum(g, axis) / B, epi_g)
        dx_all = lax.psum(dx_all, axis) / B
        gacc = _tmap(lambda g: g[None] / B, gacc)   # [1,...] per stage slice
        return loss_mean, gacc, epi_g, dx_all

    return shard_map_compat(
        local_fn, mesh, (P(axis), P(), P(), P()),
        (P(), P(axis), P(), P()), check=True)


class PipelineParallel:
    """High-level wrapper: owns stacked stage params + a train step.

    Analogue of the role ParallelWrapper plays for DP
    (`parallelism/ParallelWrapper.java:409`), but for a pipelined trunk: the
    user supplies one `stage_fn` and S per-stage param trees; `fit_batch`
    runs forward+backward+update as ONE jitted sharded computation.
    """

    def __init__(self, stage_fn, stage_params: Sequence[Any], mesh: Mesh, *,
                 loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
                 updater=None, n_micro: int = 4, axis: str = AXIS_PIPE,
                 data_axis: Optional[str] = None):
        from deeplearning4j_tpu.optim.updaters import Sgd

        self.mesh = mesh
        self.axis = axis
        self.n_stages = len(stage_params)
        self.n_micro = n_micro
        self.loss_fn = loss_fn
        self.updater = updater or Sgd(1e-2)
        stacked = stack_stage_params(stage_params)
        self.params = jax.device_put(stacked, stage_sharding(stacked, mesh, axis))
        # Optimizer state is zeros_like(params): every leaf carries the stage
        # dim leading, so one prefix spec shards the whole (differently
        # shaped) state tree.
        opt = self.updater.init(self.params)
        self.opt_state = (jax.device_put(opt, NamedSharding(mesh, P(axis)))
                          if jax.tree_util.tree_leaves(opt) else opt)
        self._fwd = make_pipeline_fn(stage_fn, self.n_stages, n_micro, mesh,
                                     axis=axis, data_axis=data_axis)
        self._step = None

    def forward(self, x):
        y = self._fwd(self.params, split_microbatches(x, self.n_micro))
        return merge_microbatches(y)

    def _build_step(self):
        fwd, loss_fn, updater = self._fwd, self.loss_fn, self.updater

        def step(params, opt_state, it, x_mb, y_mb):
            def objective(p):
                pred = fwd(p, x_mb)
                return loss_fn(pred, y_mb)

            loss, grads = jax.value_and_grad(objective)(params)
            upd, new_opt = updater.apply(grads, opt_state, params, it)
            new_params = _tmap(lambda a, b: a - b.astype(a.dtype), params, upd)
            return new_params, new_opt, loss

        # donatemon.instrument is identity with DL4J_TPU_DONATEMON off.
        return donatemon.instrument(
            jax.jit(step, donate_argnums=(0, 1)), (0, 1),
            name="PipelinedNetwork._step",
            arg_names=("params", "opt_state"))

    def fit_batch(self, x, y, it: int = 0) -> float:
        if self._step is None:
            self._step = self._build_step()
        x_mb = split_microbatches(jnp.asarray(x), self.n_micro)
        y_mb = split_microbatches(jnp.asarray(y), self.n_micro)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, jnp.asarray(it, jnp.int32),
            x_mb, y_mb)
        return float(loss)


# --------------------------------------------------------------------------
# Model-level pipelining: partition a configured MultiLayerNetwork
# --------------------------------------------------------------------------
def partition_for_pipeline(net, n_stages: int):
    """Split a MultiLayerNetwork's layers into (prologue, trunk, epilogue).

    The trunk is the longest run of consecutive layers with identical
    config class AND identical param shapes (e.g. N TransformerEncoderBlocks
    or a stack of equal DenseLayers); it is trimmed from the FRONT to a
    multiple of n_stages (trimmed layers join the prologue). Everything
    before runs as the (replicated) prologue, everything after — ending in
    the output layer — as the epilogue fused into the last pipeline stage.
    """
    layers = list(net.conf.layers)
    if getattr(net.conf, "preprocessors", None):
        raise ValueError(
            "PipelinedNetwork does not apply config preprocessors "
            f"(found at indices {sorted(net.conf.preprocessors)}); "
            "pipeline a net whose layers connect without shape adapters")
    params = net.params_tree

    import dataclasses

    def sig(l):
        sub = params[l.name]
        # Full config equality minus the name — same-shape layers with
        # different hyperparameters (activation, heads, ...) must NOT be
        # merged into one trunk, or stage_fn would run every stage with the
        # first stage's config.
        return (dataclasses.replace(l, name=None),
                tuple(sorted((k, tuple(v.shape)) for k, v in sub.items())))

    sigs = [sig(l) for l in layers]
    best = (0, 0)  # (start, length)
    i = 0
    while i < len(layers):
        j = i + 1
        while j < len(layers) and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[1]:
            best = (i, j - i)
        i = j
    start, length = best
    usable = (length // n_stages) * n_stages
    if usable < n_stages or usable == 0:
        raise ValueError(
            f"No uniform trunk of >= {n_stages} identical consecutive "
            f"layers found (longest run: {length}); pipeline parallelism "
            "needs a homogeneous trunk (transformer blocks, equal dense "
            "stack, ...)")
    trim = length - usable
    start += trim  # front-trimmed extras stay in the prologue
    pro = layers[:start]
    trunk = layers[start:start + usable]
    epi = layers[start + usable:]
    if not epi or not getattr(epi[-1], "is_output_layer", False):
        raise ValueError(
            "Pipeline epilogue must end with an output layer (loss is "
            "computed on the last stage)")
    return pro, trunk, epi


class PipelinedNetwork:
    """Train a configured MultiLayerNetwork with pipeline parallelism.

    The ParallelWrapper analogue for the `pipe` mesh axis (the reference has
    no pipeline story at all — SURVEY §2.4): partitions the net into
    prologue + uniform trunk + epilogue, shards the stacked trunk over the
    pipeline stages, and trains with the 1F1B schedule
    (`make_pipeline_1f1b_fn`) — forward, loss, backward, and update are ONE
    jitted sharded computation per batch.

    Notes: the pipelined path trains with the net's GLOBAL updater
    (per-layer updater overrides don't apply), ignores masks, and runs
    dropout-free (deterministic) forward — the reference semantics for all
    three live on the single-device path. L1/L2 regularization IS applied
    (computed directly on the param trees and added to the pipeline
    gradients — exact, since it doesn't depend on activations). Trunks
    with activity-dependent aux losses (MoE load balancing) are rejected:
    their aux terms would need threading through the hand-rolled schedule.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, *,
                 n_micro: int = 8, axis: str = AXIS_PIPE,
                 updater=None):
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        if net.params_tree is None:
            raise RuntimeError("Model must be init()ed before pipelining")
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh()
        if axis not in self.mesh.axis_names:
            raise ValueError(f"Mesh {self.mesh.axis_names} has no "
                             f"{axis!r} axis")
        self.axis = axis
        self.n_stages = S = self.mesh.shape[axis]
        self.n_micro = n_micro
        pro, trunk, epi = partition_for_pipeline(net, S)
        if any(getattr(l, "n_experts", 0) for l in trunk):
            raise ValueError(
                "MoE trunk blocks (n_experts > 0) carry an activity-"
                "dependent aux loss the pipeline schedule cannot thread; "
                "train MoE models via ParallelWrapper / expert meshes")
        self._pro_layers, self._trunk_layers, self._epi_layers = pro, trunk, epi
        self._k = len(trunk) // S          # layers per stage
        K = self._k

        self.pro_params = {l.name: net.params_tree[l.name] for l in pro}
        self.epi_params = {l.name: net.params_tree[l.name] for l in epi}
        stage_trees = [
            {f"b{j}": net.params_tree[trunk[i * K + j].name]
             for j in range(K)}
            for i in range(S)
        ]
        stacked = stack_stage_params(stage_trees)
        self.trunk_params = jax.device_put(
            stacked, stage_sharding(stacked, self.mesh, axis))
        rep = NamedSharding(self.mesh, P())
        self.pro_params = jax.device_put(self.pro_params, rep)
        self.epi_params = jax.device_put(self.epi_params, rep)

        self.updater = updater if updater is not None else net.conf.updater
        params_all = {"pro": self.pro_params, "trunk": self.trunk_params,
                      "epi": self.epi_params}
        self.opt_state = self.updater.init(params_all)

        block_cfgs = trunk[:K]   # identical configs; names differ only

        def stage_fn(sp, x):
            for j, cfg in enumerate(block_cfgs):
                x, _ = cfg.apply(sp[f"b{j}"], x, train=True, rng=None)
            return x

        def last_loss(ep, y, label):
            x = y
            for l in epi[:-1]:
                x, _ = l.apply(ep[l.name], x, train=True, rng=None)
            out = epi[-1]
            return out.score(ep[out.name], x, label, None)

        def prologue_fn(pp, x):
            for l in pro:
                x, _ = l.apply(pp[l.name], x, train=True, rng=None)
            return x

        self._prologue_fn = prologue_fn
        self._block_cfgs = block_cfgs
        self._has_reg = any(
            (l.l1 or l.l2 or l.l1_bias or l.l2_bias)
            for l in (*pro, *trunk, *epi))
        self._pipe = make_pipeline_1f1b_fn(
            stage_fn, last_loss, S, n_micro, self.mesh, axis=axis)
        self._step = None

    # ------------------------------------------------------------- train
    def _build_step(self):
        pipe, prologue_fn, updater = self._pipe, self._prologue_fn, self.updater
        n_micro = self.n_micro
        pro_layers, epi_layers = self._pro_layers, self._epi_layers
        block_cfgs, has_reg = self._block_cfgs, self._has_reg

        def reg_fn(params_all):
            """L1/L2 over all groups — purely param-dependent, so it adds
            to the pipeline gradients exactly without touching the
            schedule (trunk blocks share coefficients, so summing over the
            stacked stage axis equals the per-stage sum)."""
            total = jnp.asarray(0.0, jnp.float32)
            for l in pro_layers:
                total = total + l.regularization(params_all["pro"][l.name])
            for j, cfg in enumerate(block_cfgs):
                total = total + cfg.regularization(
                    params_all["trunk"][f"b{j}"])
            for l in epi_layers:
                total = total + l.regularization(params_all["epi"][l.name])
            return total

        def step(params_all, opt_state, it, x, lab_mb):
            pro_p, trunk_p, epi_p = (params_all["pro"], params_all["trunk"],
                                     params_all["epi"])
            # graft: allow(GL003): pytree emptiness test — `pro_p` is a
            # params dict, so truthiness is static under trace
            if pro_p:
                pro_out, pro_vjp = jax.vjp(
                    lambda p: prologue_fn(p, x), pro_p)
            else:
                pro_out = x
            pro_mb = split_microbatches(pro_out, n_micro)
            loss, trunk_g, epi_g, dx_mb = pipe(trunk_p, epi_p, pro_mb,
                                               lab_mb)
            grads = {"trunk": trunk_g, "epi": epi_g}
            # graft: allow(GL003): pytree emptiness test (static)
            if pro_p:
                (grads["pro"],) = pro_vjp(merge_microbatches(dx_mb))
            else:
                grads["pro"] = {}
            if has_reg:
                reg_loss, reg_g = jax.value_and_grad(reg_fn)(params_all)
                loss = loss + reg_loss
                grads = _tmap(lambda a, b: a + b.astype(a.dtype),
                              grads, reg_g)
            upd, new_opt = updater.apply(grads, opt_state, params_all, it)
            new_params = _tmap(lambda a, b: a - b.astype(a.dtype),
                               params_all, upd)
            return new_params, new_opt, loss

        # donatemon.instrument is identity with DL4J_TPU_DONATEMON off.
        return donatemon.instrument(
            jax.jit(step, donate_argnums=(0, 1)), (0, 1),
            name="PipelineParallel._step",
            arg_names=("params", "opt_state"))

    def fit_batch(self, x, labels, it: Optional[int] = None) -> float:
        net = self.net
        if self._step is None:
            self._step = self._build_step()
        if it is None:
            it = net.iteration
        x = jnp.asarray(x, net.dtype)
        lab_mb = split_microbatches(jnp.asarray(labels), self.n_micro)
        params_all = {"pro": self.pro_params, "trunk": self.trunk_params,
                      "epi": self.epi_params}
        params_all, self.opt_state, loss = self._step(
            params_all, self.opt_state, jnp.asarray(it, jnp.int32),
            x, lab_mb)
        self.pro_params = params_all["pro"]
        self.trunk_params = params_all["trunk"]
        self.epi_params = params_all["epi"]
        return float(loss)

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128):
        from deeplearning4j_tpu.data.iterators import as_iterator

        net = self.net
        it = as_iterator(data, labels, batch_size)
        for l in net.listeners:
            l.on_fit_start(net)
        for _ in range(epochs):
            for l in net.listeners:
                l.on_epoch_start(net, net.epoch)
            for ds in it:
                feats, labs = ds.features, ds.labels
                b = feats.shape[0]
                if b % self.n_micro:
                    # pad trailing partial batches by repetition so the
                    # microbatch split keeps its static shape (the same
                    # policy as ParallelWrapper._pad_to_divisible)
                    pad = self.n_micro - (b % self.n_micro)
                    idx = np.concatenate(
                        [np.arange(b), np.zeros(pad, np.int64)])
                    feats, labs = feats[idx], labs[idx]
                loss = self.fit_batch(feats, labs)
                net.score_ = loss
                net.iteration += 1
                for l in net.listeners:
                    l.iteration_done(net, net.iteration, net.epoch, loss)
            # refresh net.params_tree per epoch so listeners reading param/
            # update stats (StatsListener) see trained weights, not init
            self.sync_to_net()
            for l in net.listeners:
                l.on_epoch_end(net, net.epoch)
            net.epoch += 1
        for l in net.listeners:
            l.on_fit_end(net)
        self.sync_to_net()
        return net

    # ------------------------------------------------------------ output
    def sync_to_net(self):
        """Write pipeline params back into the wrapped net (so output()/
        evaluate()/save_model see the trained weights)."""
        net, K = self.net, self._k
        for l in self._pro_layers:
            # graft: allow-sync(host writeback, off the step path)
            net.params_tree[l.name] = jax.device_get(self.pro_params[l.name])
        for l in self._epi_layers:
            # graft: allow-sync(host writeback, off the step path)
            net.params_tree[l.name] = jax.device_get(self.epi_params[l.name])
        # graft: allow-sync(host writeback, off the step path)
        stage_trees = unstack_stage_params(jax.device_get(self.trunk_params))
        for i, tree in enumerate(stage_trees):
            for j in range(K):
                name = self._trunk_layers[i * K + j].name
                net.params_tree[name] = tree[f"b{j}"]
        return net
