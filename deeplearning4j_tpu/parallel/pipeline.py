"""Pipeline parallelism — GPipe-style microbatch schedule over the `pipe`
mesh axis.

No reference counterpart: DL4J implements only data parallelism (SURVEY
§2.4 enumerates all five flavors); pipeline parallelism is one of the
green-field TPU-scale extensions demanded by SURVEY §7 step 7.

TPU-first design:
- Stages are STACKED: every stage has an identical parameter pytree and the
  per-stage leaves are stacked on a leading axis that is sharded over the
  `pipe` mesh axis. Each device therefore holds exactly its stage's weights
  (transformer-block style; heterogeneous prologue/epilogue layers live
  outside the pipelined trunk).
- The schedule is a single `lax.scan` over ticks inside `shard_map`;
  activations move stage→stage via `lax.ppermute` — a point-to-point ICI
  transfer, not a broadcast. With B microbatches and S stages, the scan runs
  B + S - 1 ticks (the classic GPipe fill+drain bubble).
- Backward is *derived*: `jax.grad` through scan + ppermute yields the
  reverse pipeline schedule automatically (ppermute's transpose is the
  reverse permutation) — no hand-written backward pass.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # older jax keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import AXIS_DATA, AXIS_PIPE

_tmap = jax.tree_util.tree_map


def stack_stage_params(stage_params: Sequence[Any]):
    """Stack S structurally-identical per-stage pytrees on a new leading
    axis (the axis that gets sharded over `pipe`)."""
    return _tmap(lambda *xs: jnp.stack(xs), *stage_params)


def unstack_stage_params(stacked) -> List[Any]:
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    n = leaves[0].shape[0]
    return [
        jax.tree_util.tree_unflatten(treedef, [l[i] for l in leaves])
        for i in range(n)
    ]


def stage_sharding(stacked, mesh: Mesh, axis: str = AXIS_PIPE):
    """NamedShardings placing stage i's slice on pipe-coordinate i."""
    return _tmap(lambda _: NamedSharding(mesh, P(axis)), stacked)


def split_microbatches(x, n_micro: int):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def merge_microbatches(y):
    return y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])


def make_pipeline_fn(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     n_stages: int, n_micro: int, mesh: Mesh, *,
                     axis: str = AXIS_PIPE,
                     data_axis: Optional[str] = None):
    """Build f(stacked_params, x_mb) -> y_mb running the GPipe schedule.

    stage_fn: (one stage's params, activations [mb, ...]) -> [mb, ...];
      activation shape must be stage-invariant (uniform-trunk restriction).
    x_mb / y_mb: [n_micro, mb, ...]. If `data_axis` is given, the per-
      microbatch batch dim is additionally sharded over it (2-D pipe×data).
    """
    if n_stages < 1:
        raise ValueError("n_stages must be >= 1")
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    total_ticks = n_micro + n_stages - 1

    def local_fn(params_shard, x_mb):
        my_params = _tmap(lambda p: p[0], params_shard)
        stage = lax.axis_index(axis)

        def tick(buf, t):
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            feed = lax.dynamic_index_in_dim(x_mb, mb_idx, keepdims=False)
            inp = jnp.where(stage == 0, feed, buf)
            out = stage_fn(my_params, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        # Mark the carry as device-varying over `pipe` (jax 0.9 vma typing:
        # the ppermute output is varying, so the initial carry must be too).
        buf0 = lax.pcast(jnp.zeros_like(x_mb[0]), (axis,), to="varying")
        _, outs = lax.scan(tick, buf0, jnp.arange(total_ticks))
        # Last stage's outputs for microbatch m appear at tick m + S - 1.
        tail = lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro, axis=0)
        mask = (stage == n_stages - 1).astype(tail.dtype)
        return lax.psum(tail * mask, axis)

    in_x = P(None, data_axis) if data_axis else P()
    out_y = P(None, data_axis) if data_axis else P()
    return shard_map(local_fn, mesh=mesh,
                     in_specs=(P(axis), in_x), out_specs=out_y)


class PipelineParallel:
    """High-level wrapper: owns stacked stage params + a train step.

    Analogue of the role ParallelWrapper plays for DP
    (`parallelism/ParallelWrapper.java:409`), but for a pipelined trunk: the
    user supplies one `stage_fn` and S per-stage param trees; `fit_batch`
    runs forward+backward+update as ONE jitted sharded computation.
    """

    def __init__(self, stage_fn, stage_params: Sequence[Any], mesh: Mesh, *,
                 loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
                 updater=None, n_micro: int = 4, axis: str = AXIS_PIPE,
                 data_axis: Optional[str] = None):
        from deeplearning4j_tpu.optim.updaters import Sgd

        self.mesh = mesh
        self.axis = axis
        self.n_stages = len(stage_params)
        self.n_micro = n_micro
        self.loss_fn = loss_fn
        self.updater = updater or Sgd(1e-2)
        stacked = stack_stage_params(stage_params)
        self.params = jax.device_put(stacked, stage_sharding(stacked, mesh, axis))
        # Optimizer state is zeros_like(params): every leaf carries the stage
        # dim leading, so one prefix spec shards the whole (differently
        # shaped) state tree.
        opt = self.updater.init(self.params)
        self.opt_state = (jax.device_put(opt, NamedSharding(mesh, P(axis)))
                          if jax.tree_util.tree_leaves(opt) else opt)
        self._fwd = make_pipeline_fn(stage_fn, self.n_stages, n_micro, mesh,
                                     axis=axis, data_axis=data_axis)
        self._step = None

    def forward(self, x):
        y = self._fwd(self.params, split_microbatches(x, self.n_micro))
        return merge_microbatches(y)

    def _build_step(self):
        fwd, loss_fn, updater = self._fwd, self.loss_fn, self.updater

        def step(params, opt_state, it, x_mb, y_mb):
            def objective(p):
                pred = fwd(p, x_mb)
                return loss_fn(pred, y_mb)

            loss, grads = jax.value_and_grad(objective)(params)
            upd, new_opt = updater.apply(grads, opt_state, params, it)
            new_params = _tmap(lambda a, b: a - b.astype(a.dtype), params, upd)
            return new_params, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def fit_batch(self, x, y, it: int = 0) -> float:
        if self._step is None:
            self._step = self._build_step()
        x_mb = split_microbatches(jnp.asarray(x), self.n_micro)
        y_mb = split_microbatches(jnp.asarray(y), self.n_micro)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, jnp.asarray(it, jnp.int32),
            x_mb, y_mb)
        return float(loss)
