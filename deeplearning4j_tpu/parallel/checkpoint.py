"""Sharded, asynchronous training checkpoints with exact resume.

SURVEY §7 step 4 ("checkpoint zip ↦ sharded async ckpt") and §5 (the
reference has NO sharded checkpoints and no elastic recovery — this is a
required capability extension). Reference precedent for the artifact set:
`util/ModelSerializer.java:37-119` (params + updater state + config);
on top of that the full LOOP state is captured — iteration, epoch,
position inside the epoch's iterator, and the training RNG key — so a
killed run resumes producing bit-identical losses.

Design (TPU-native, multi-host-shaped):
- Each leaf of the params/updater/state pytrees is saved as its set of
  UNIQUE addressable device shards (one .npy per distinct shard index), so
  an FSDP-sharded tensor writes 1/N of its bytes per host and a replicated
  tensor writes one copy — no host-side gather of the global array.
- Each process writes only its own `process-<k>/` subdirectory + manifest;
  restore unions all processes' manifests (single-host: one directory).
- Async: device→host snapshot happens synchronously (the train loop
  donates buffers, so shards must be copied out before the next step), the
  file writes happen on a background thread — the step loop never blocks
  on disk.
- A checkpoint directory is only valid once `COMMIT` exists (written
  last), so a kill mid-write never yields a half checkpoint. The
  `fault_hook` seam lets `parallel/chaos.py` kill the writer at an exact
  file boundary, which is how the COMMIT protocol is CI-tested.
- Restore re-assembles each leaf's GLOBAL array from whatever shards the
  committed manifests cover and re-shards it onto the restoring mesh —
  so a snapshot taken on N devices restores onto M devices (elastic
  shrink/grow) without a host-side gather at save time.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

_MANIFEST = "manifest.json"
_COMMIT = "COMMIT"


# --------------------------------------------------------------- pytree IO
def _flatten(tree, prefix="") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        pass
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _unflatten_into(flat: Dict[str, np.ndarray], like, device_put=None):
    """Rebuild `like`'s structure from path-keyed arrays; leaves missing
    from `flat` keep their current value."""
    def rebuild(sub, prefix, sharding):
        if isinstance(sub, dict):
            return {k: rebuild(v, f"{prefix}{k}/",
                               sharding.get(k) if isinstance(sharding, dict)
                               else None)
                    for k, v in sub.items()}
        if isinstance(sub, (list, tuple)):
            return type(sub)(
                rebuild(v, f"{prefix}{i}/",
                        sharding[i] if isinstance(sharding, (list, tuple))
                        else None)
                for i, v in enumerate(sub))
        key = prefix.rstrip("/")
        if key not in flat:
            return sub
        arr = flat[key]
        if device_put is not None:
            return device_put(key, arr, sub, sharding)
        return jax.numpy.asarray(arr)
    return rebuild(like, "", device_put and {})


def _index_bounds(index: Tuple, shape: Tuple[int, ...]) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[lo, hi], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        lo = 0 if sl.start is None else int(sl.start)
        hi = dim if sl.stop is None else int(sl.stop)
        out.append([lo, hi])
    return out


def _snapshot_leaf(arr) -> List[Tuple[List[List[int]], np.ndarray]]:
    """Unique addressable shards of a jax.Array as host copies.
    Replicated arrays (every shard covering the full index) collapse to a
    single entry; FSDP-sharded arrays yield one entry per distinct slice."""
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        return [(_index_bounds((), a.shape), a)]
    seen: Dict[Tuple, Any] = {}
    for sh in arr.addressable_shards:
        key = tuple(
            (None if s.start is None else int(s.start),
             None if s.stop is None else int(s.stop))
            for s in sh.index)
        if key not in seen:
            seen[key] = sh
    return [(_index_bounds(sh.index, arr.shape), np.asarray(sh.data))
            for sh in seen.values()]


# ------------------------------------------------------------ checkpointer
class ShardedCheckpointer:
    """Save/restore sharded training snapshots with rotation + async IO.

    `save()` returns as soon as device shards are copied to host; writing
    happens on a daemon thread. `restore_into()` rebuilds the model trees
    (re-sharded onto the wrapper's mesh when one is supplied) and returns
    the loop position for exact resume."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        # writer-thread error latch + restore pins share one lock: both
        # are cross-thread (writer appends/rotates, main thread drains/reads)
        self._state_lock = threading.Lock()
        self._errors: List[BaseException] = []
        self._pinned: set = set()
        # chaos seam: fn(kind, path) called before every file write
        # ("shard" | "manifest" | "commit"); raising simulates the writer
        # dying mid-checkpoint at a deterministic file boundary
        self.fault_hook: Optional[Any] = None

    # ------------------------------------------------------------- save
    def save(self, net, *, step: int, position: Optional[Dict] = None):
        """Snapshot params/updater/state + loop position at `step`."""
        payload = {}
        for name, tree in (("params", net.params_tree),
                           ("updater", net.updater_state),
                           ("state", net.state_tree)):
            flat = _flatten(tree)
            payload[name] = {k: _snapshot_leaf(v) for k, v in flat.items()}
        rng = getattr(net, "_rng", None)
        if rng is not None:
            try:
                rng = jax.random.key_data(rng)  # typed PRNG keys
            except Exception:  # graft: allow(GL403): legacy raw key stays
                pass                            # legacy uint32 key arrays
        meta = {
            "step": int(step),
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
            "position": position or {},
            # graft: allow-sync(checkpoint metadata serializes the rng key)
            "rng": None if rng is None else np.asarray(rng).tolist(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
        leaf_meta = {
            name: {k: {"shape": list(np.asarray(shards[0][1]).shape)
                       if shards[0][0] == [] or not shards[0][0]
                       else None,
                       "dtype": str(shards[0][1].dtype)}
                   for k, shards in payload[name].items()}
            for name in payload
        }
        # global shape per leaf: from the live tree (host obtains it freely)
        for name, tree in (("params", net.params_tree),
                           ("updater", net.updater_state),
                           ("state", net.state_tree)):
            for k, v in _flatten(tree).items():
                leaf_meta[name][k]["shape"] = list(np.shape(v))
        job = (dict(payload), meta, leaf_meta)
        if self.async_save:
            self._ensure_worker()
            self._q.put(job)
        else:
            self._write(job)
        return self

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            # graft: allow(GL301): only save()'s caller thread spawns the
            # writer; the worker itself never touches self._worker
            self._worker = threading.Thread(
                target=self._drain, daemon=True, name="ckpt-writer")
            self._worker.start()

    def _drain(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                self._write(job)
            except BaseException as e:  # surfaced by wait()
                with self._state_lock:
                    self._errors.append(e)
            finally:
                self._q.task_done()

    def _touch(self, kind: str, path: str) -> None:
        hook = self.fault_hook
        if hook is not None:
            hook(kind, path)

    def _write(self, job):
        payload, meta, leaf_meta = job
        step = meta["step"]
        proc = meta["process_index"]
        d = os.path.join(self.directory, f"step-{step:010d}")
        pdir = os.path.join(d, f"process-{proc}")
        os.makedirs(pdir, exist_ok=True)
        manifest = {"meta": meta, "leaves": {}}
        fid = 0
        for name, leaves in payload.items():
            for key, shards in leaves.items():
                entries = []
                for bounds, data in shards:
                    fn = f"s{fid:06d}.npy"
                    fid += 1
                    path = os.path.join(pdir, fn)
                    self._touch("shard", path)
                    np.save(path, data)
                    entries.append({"index": bounds, "file": fn})
                manifest["leaves"][f"{name}:{key}"] = {
                    "shards": entries, **leaf_meta[name][key]}
        mpath = os.path.join(pdir, _MANIFEST)
        self._touch("manifest", mpath)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        cpath = os.path.join(pdir, _COMMIT)
        self._touch("commit", cpath)
        with open(cpath, "w") as f:
            f.write("ok")
        self._rotate()

    def _rotate(self):
        with self._state_lock:
            pinned = set(self._pinned)
        for s in self.steps()[:-self.max_to_keep]:
            if s in pinned:
                # a restore is (or was just about to start) reading this
                # step — deleting it under the reader loses the recovery
                continue
            shutil.rmtree(
                os.path.join(self.directory, f"step-{s:010d}"),
                ignore_errors=True)

    def wait(self):
        """Block until queued writes land; re-raise writer errors.

        The error latch is drained on raise: one failed write surfaces
        exactly once, instead of poisoning every later wait()."""
        if self._worker is not None and self._worker.is_alive():
            self._q.join()
        with self._state_lock:
            errors, self._errors = self._errors, []
        if errors:
            raise errors[0]
        return self

    # ---------------------------------------------------------- restore
    def steps(self) -> List[int]:
        out = []
        for n in os.listdir(self.directory):
            if not n.startswith("step-"):
                continue
            d = os.path.join(self.directory, n)
            try:
                committed = any(
                    os.path.exists(os.path.join(d, p, _COMMIT))
                    for p in os.listdir(d))
            except OSError:
                # the writer thread's _rotate() can delete a step-* dir
                # between our listdir of the parent and of the step (or
                # a stray non-directory entry matched the prefix) —
                # a vanished step is simply not a candidate
                continue
            if committed:
                out.append(int(n[len("step-"):]))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def _read_step(self, step: int):
        # pin the step for the duration of the read so the writer
        # thread's rotation can never delete it out from under us
        with self._state_lock:
            self._pinned.add(step)
        try:
            return self._read_step_pinned(step)
        finally:
            with self._state_lock:
                self._pinned.discard(step)

    def _read_step_pinned(self, step: int):
        d = os.path.join(self.directory, f"step-{step:010d}")
        flats: Dict[str, Dict[str, np.ndarray]] = {}
        meta = None
        try:
            pnames = sorted(os.listdir(d))
        except OSError:
            pnames = []    # rotated away before the pin landed
        for pname in pnames:
            pdir = os.path.join(d, pname)
            mf = os.path.join(pdir, _MANIFEST)
            if not os.path.exists(mf) or \
                    not os.path.exists(os.path.join(pdir, _COMMIT)):
                continue
            with open(mf) as f:
                manifest = json.load(f)
            meta = meta or manifest["meta"]
            for full_key, info in manifest["leaves"].items():
                name, key = full_key.split(":", 1)
                shape = tuple(info["shape"])
                tgt = flats.setdefault(name, {})
                if key not in tgt:
                    tgt[key] = np.empty(shape, dtype=np.dtype(info["dtype"]))
                for entry in info["shards"]:
                    data = np.load(os.path.join(pdir, entry["file"]))
                    idx = tuple(slice(lo, hi) for lo, hi in entry["index"])
                    tgt[key][idx] = data
        if meta is None:
            raise FileNotFoundError(
                f"No committed checkpoint for step {step} in {self.directory}")
        return flats, meta

    def restore_into(self, net, *, step: Optional[int] = None,
                     shardings: Optional[Dict[str, Any]] = None) -> Dict:
        """Load a checkpoint into a model. `shardings` optionally maps
        {'params': tree, 'updater': tree, 'state': tree} of NamedShardings
        (e.g. a ParallelWrapper's) so restored leaves land sharded on the
        mesh rather than on one device. Returns the loop position."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoints in {self.directory}")
        flats, meta = self._read_step(step)

        def put(kind):
            sh_tree = (shardings or {}).get(kind)
            sh_flat = _flatten(sh_tree) if sh_tree is not None else {}

            def device_put(key, arr, current, _):
                sh = sh_flat.get(key)
                a = jax.numpy.asarray(
                    arr, getattr(current, "dtype", None))
                return jax.device_put(a, sh) if sh is not None else a
            return device_put

        if "params" in flats:
            net.params_tree = _unflatten_into(
                flats["params"], net.params_tree, put("params"))
        if "updater" in flats and net.updater_state is not None:
            net.updater_state = _unflatten_into(
                flats["updater"], net.updater_state, put("updater"))
        if "state" in flats and net.state_tree:
            net.state_tree = _unflatten_into(
                flats["state"], net.state_tree, put("state"))
        net.iteration = int(meta["iteration"])
        net.epoch = int(meta["epoch"])
        if meta.get("rng") is not None and getattr(net, "_rng", None) is not None:
            kd = np.asarray(meta["rng"], dtype=np.uint32)
            try:
                if jax.numpy.issubdtype(net._rng.dtype, jax.dtypes.prng_key):
                    net._rng = jax.random.wrap_key_data(kd)
                else:
                    net._rng = jax.numpy.asarray(kd)
            except Exception:
                net._rng = jax.numpy.asarray(kd)
        return dict(meta["position"])

    def restore_into_wrapper(self, wrapper, *,
                             step: Optional[int] = None) -> Dict:
        """Restore into a ParallelWrapper's model with ITS shardings —
        FSDP-sharded params AND replica-sharded optimizer moments land
        straight back on the mesh. The wrapper's spine may sit on a
        DIFFERENT device count than the snapshot's (elastic shrink/grow):
        `_read_step` re-assembles each global array from the saved unique
        shards, then the device_put here re-partitions it under the
        restoring spine's specs."""
        shardings = {"params": wrapper._params_sh,
                     "updater": wrapper._opt_sh}
        if wrapper.net.state_tree:
            shardings["state"] = wrapper.spine.state_shardings(
                wrapper.net.state_tree)
        return self.restore_into(wrapper.net, step=step,
                                 shardings=shardings)
