"""Parameter/batch sharding rules.

The reference has no tensor-parallel story (SURVEY §2.4: data parallel only);
these rules are the green-field extension that maps layer param trees onto
mesh axes. GSPMD then partitions the jitted step — matmuls become
local matmuls + ICI collectives without manual comms code (pjit idiom,
scaling-book recipe: annotate shardings, let XLA insert collectives).

Rule model: a ShardingRules maps (layer_name, param_name) → PartitionSpec by
first-match over (layer_glob, param_name) patterns. Defaults implement
Megatron-style alternating column/row parallel for Dense/Conv/LSTM stacks.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL


@dataclasses.dataclass
class ShardingRules:
    """Ordered (layer_glob, param_name_glob) → PartitionSpec rules."""

    rules: List[Tuple[str, str, P]] = dataclasses.field(default_factory=list)
    default: P = dataclasses.field(default_factory=P)

    def spec_for(self, layer_name: str, param_name: str) -> P:
        for lg, pg, spec in self.rules:
            if fnmatch.fnmatch(layer_name, lg) and fnmatch.fnmatch(param_name, pg):
                return spec
        return self.default

    def tree_specs(self, params: Dict) -> Dict:
        """PartitionSpec pytree matching a {layer: {param: array}} tree."""
        def leaf_specs(layer_name, sub, path=""):
            out = {}
            for k, v in sub.items():
                if isinstance(v, dict):
                    out[k] = leaf_specs(layer_name, v, path + k + "/")
                else:
                    out[k] = self.spec_for(layer_name, path + k)
            return out
        return {ln: leaf_specs(ln, sub) for ln, sub in params.items()}


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int, axis: str = AXIS_DATA) -> NamedSharding:
    """Shard the leading (batch) dim over `axis`, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_params(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Place a param tree on the mesh per rules (device_put with
    NamedSharding). With no rules: fully replicated."""
    if rules is None:
        sharding = replicate(mesh)
        return jax.device_put(params, sharding)
    specs = rules.tree_specs(params)

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, params, specs)


def tensor_parallel_rules(layer_names: List[str],
                          axis: str = AXIS_MODEL) -> ShardingRules:
    """Megatron-style alternating column/row parallel over a sequential
    stack: even layers shard the OUTPUT dim (column parallel, spec
    (None, model)), odd layers shard the INPUT dim (row parallel,
    (model, None)) so activations stay sharded across the pair with a single
    psum at the row-parallel output. Biases follow the output dim; the final
    (output/classifier) layer is replicated for exact loss semantics."""
    rules: List[Tuple[str, str, P]] = []
    n = len(layer_names)
    for i, name in enumerate(layer_names):
        if i == n - 1:
            rules.append((name, "*", P()))
            continue
        if i % 2 == 0:
            rules.append((name, "W", P(None, axis)))
            rules.append((name, "RW", P(None, axis)))
            rules.append((name, "b", P(axis)))
        else:
            rules.append((name, "W", P(axis, None)))
            rules.append((name, "RW", P(axis, None)))
            rules.append((name, "b", P()))
    return ShardingRules(rules=rules)


def conv_channel_rules(layer_names: List[str], axis: str = AXIS_MODEL
                       ) -> ShardingRules:
    """Channel-parallel conv stacks: shard conv kernels on the output-channel
    dim (HWIO → spec (None, None, None, model)); replicate the classifier."""
    rules: List[Tuple[str, str, P]] = []
    for i, name in enumerate(layer_names):
        if i == len(layer_names) - 1:
            rules.append((name, "*", P()))
        else:
            rules.append((name, "W", P(None, None, None, axis)))
            rules.append((name, "b", P(axis)))
    return ShardingRules(rules=rules)


def fsdp_rules(layer_names: List[str], axis: str = AXIS_DATA) -> ShardingRules:
    """ZeRO/FSDP-style: shard every large param's FIRST dim over the data
    axis — optimizer state shards with it (cross-replica weight-update
    sharding, cf. PAPERS.md 'Automatic Cross-Replica Sharding of Weight
    Update in Data-Parallel Training'). XLA all-gathers weights per layer
    on use and reduce-scatters grads."""
    return ShardingRules(rules=[("*", "W", P(axis)), ("*", "RW", P(axis))])
