"""Asynchronous parameter-server training (hogwild-style, bounded
staleness).

Reference parity: data-parallel flavor #4/#5 in SURVEY §2.4 — the Aeron
UDP parameter server (`ParameterServerTrainerContext.java:20,38-40`
launching `ParameterServerNode`, workers push/pull via
`ParameterServerClient` in `ParameterServerTrainer.java:32`) and the
hogwild `VectorCalculationsThread`s of SequenceVectors. The round-1
verdict accepted "subsumed by ICI" for the daemon itself but flagged that
NO async training mode existed at all — this module supplies it.

TPU-native redesign: the server is an in-process host-side object (no UDP
daemon — DCN coordination belongs to jax.distributed); workers are
threads that PULL a versioned snapshot, compute gradients with the
model's jitted loss on their data shard, and PUSH asynchronously — no
barrier, updates apply in arrival order onto whatever the current params
are (gradient-level hogwild; a lock per apply prevents torn pytrees,
matching the reference's per-array atomicity). `staleness_limit` gives
SSP (stale-synchronous) semantics: pushes computed against a snapshot
older than the limit are dropped and counted, the usual taming of async
divergence."""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_tmap = jax.tree_util.tree_map


class AsyncParameterServer:
    """Versioned host-side parameter store. Reference role:
    `ParameterServerNode` + `ParameterServerClient` push/pull."""

    def __init__(self, params, updater, *, staleness_limit: Optional[int] = None):
        self._params = params
        self._updater = updater
        self._opt_state = updater.init(params)
        self._version = 0
        self._lock = threading.Lock()
        self.staleness_limit = staleness_limit
        # telemetry (reference: PS exposes counters through its REST seam)
        self.pushes = 0
        self.rejected = 0
        self.max_staleness = 0

    def pull(self):
        """-> (version, params). Reference: ParameterServerClient.getArray."""
        with self._lock:
            return self._version, self._params

    def push(self, grads, version: int) -> bool:
        """Apply one gradient contribution computed against `version`.
        Returns False (dropped) when staleness exceeds the limit.
        Reference: ParameterServerClient.pushNDArray."""
        with self._lock:
            staleness = self._version - version
            self.max_staleness = max(self.max_staleness, staleness)
            if self.staleness_limit is not None and \
                    staleness > self.staleness_limit:
                self.rejected += 1
                return False
            upd, self._opt_state = self._updater.apply(
                grads, self._opt_state, self._params,
                jnp.asarray(self._version, jnp.int32))
            self._params = _tmap(
                lambda p, u: p - u.astype(p.dtype), self._params, upd)
            self._version += 1
            self.pushes += 1
            return True

    @property
    def version(self) -> int:
        with self._lock:
            return self._version


class AsyncTrainer:
    """Hogwild-style trainer: N worker threads pulling/pushing against one
    AsyncParameterServer. Reference: `ParameterServerTrainer.java:32`
    (feed → fit on replica → push) without its per-batch blocking pull.

    The model's params land back on the net when fit() returns."""

    def __init__(self, net, *, num_workers: int = 4,
                 staleness_limit: Optional[int] = None,
                 updater=None):
        from deeplearning4j_tpu.optim.updaters import resolve_updater

        if net.params_tree is None:
            raise RuntimeError("Model must be init()ed first")
        self.net = net
        self.num_workers = num_workers
        self.updater = resolve_updater(
            updater if updater is not None
            else (net.conf.updater or "sgd"))
        self.staleness_limit = staleness_limit
        self.server: Optional[AsyncParameterServer] = None

    def fit(self, data, labels, *, iterations_per_worker: int = 20,
            batch_size: int = 32, seed: int = 0) -> "AsyncTrainer":
        net = self.net
        x = np.asarray(data)
        y = np.asarray(labels)
        # never give a worker an empty partition
        n_workers = max(1, min(self.num_workers, len(x)))
        self.server = AsyncParameterServer(
            net.params_tree, self.updater,
            staleness_limit=self.staleness_limit)
        states = net.state_tree

        @jax.jit
        # graft: allow(GL102): one closure per fit(), warmed once below;
        # all worker threads share the same jitted callable
        def grad_fn(params, feats, labs):
            def loss_fn(p):
                loss, _ = net._loss(p, states, feats, labs, None, None,
                                    None, train=True)
                return loss
            return jax.grad(loss_fn)(params)

        # warm the jit cache once so threads don't race the first trace
        grad_fn(net.params_tree,
                jnp.asarray(x[:batch_size], net.dtype),
                jnp.asarray(y[:batch_size]))

        errors: List[BaseException] = []

        def worker(w: int):
            try:
                rng = np.random.default_rng(seed + 7919 * w)
                part = np.arange(w, len(x), n_workers)
                for _ in range(iterations_per_worker):
                    sel = part[rng.integers(0, len(part), batch_size)]
                    version, params = self.server.pull()
                    grads = grad_fn(params, jnp.asarray(x[sel], net.dtype),
                                    jnp.asarray(y[sel]))
                    self.server.push(grads, version)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        _, net.params_tree = self.server.pull()
        net.iteration += self.server.pushes
        return self
