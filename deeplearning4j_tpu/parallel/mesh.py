"""Device-mesh construction.

The mesh is the TPU-native replacement for the reference's device zoo
(`ParallelWrapper.createZooIfNeccessary:539-553` pinning threads to GPUs via
AffinityManager): instead of N threads × N model replicas, ONE program is
compiled over a `jax.sharding.Mesh` and XLA lays collectives onto ICI.

Axis conventions (used by all trainers/rules in this package):
  data  — batch (data parallel)
  model — tensor parallel (hidden/feature dims)
  pipe  — pipeline stages
  seq   — sequence/context parallel (ring attention)
  expert — MoE expert parallel
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes; -1 for one axis means 'all remaining devices'."""

    axes: Dict[str, int]

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) or 1
        if len(wild) > 1:
            raise ValueError("At most one axis may be -1")
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"Mesh axes {sizes} use {total} devices but {n_devices} "
                f"are available")
        return sizes


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. Default: 1-D data-parallel over all devices.

    On multi-host TPU slices, `jax.devices()` is globally ordered so the
    trailing mesh axes land on ICI-adjacent chips — put the
    highest-bandwidth-demand axis (model/seq) LAST, data FIRST so its
    collectives can ride DCN if the mesh spans slices (scaling-book recipe).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {AXIS_DATA: len(devices)}
    sizes = MeshSpec(dict(axes)).resolve(len(devices))
    names = tuple(sizes)
    shape = tuple(sizes[n] for n in names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, names)


def shard_map_compat(fn, mesh, in_specs, out_specs, *, check: bool = False):
    """One shard_map entry point across jax versions: new-API
    `jax.shard_map` (check_vma) or the old experimental import
    (check_rep). Every shard_map call site in the package routes
    through here so an API change is a one-line fix. `check=True`
    keeps jax's default replication/vma checking (pipeline's psum-
    reduced outputs pass it); False disables it (ring attention's
    merged partials do not)."""
    try:
        from jax import shard_map
        kw = {} if check else {"check_vma": False}
    except ImportError:                      # older jax
        from jax.experimental.shard_map import shard_map
        kw = {} if check else {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)
