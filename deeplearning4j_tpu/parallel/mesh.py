"""Device-mesh construction — and the ONE sharding spine.

The mesh is the TPU-native replacement for the reference's device zoo
(`ParallelWrapper.createZooIfNeccessary:539-553` pinning threads to GPUs via
AffinityManager): instead of N threads × N model replicas, ONE program is
compiled over a `jax.sharding.Mesh` and XLA lays collectives onto ICI.

Axis conventions (used by all trainers/rules in this package):
  data  — batch (data parallel)
  model — tensor parallel (hidden/feature dims)
  pipe  — pipeline stages
  seq   — sequence/context parallel (ring attention)
  expert — MoE expert parallel

This module is also the single OWNER of placement: `MeshContext` bundles
the mesh with one `ShardingRules` and derives every sharding the trainers
need (batch, params, optimizer state, replicated). Everything downstream
(`ParallelWrapper`, `TrainingExecutor`, `DevicePrefetchIterator`,
checkpoint restore) consumes the context instead of inventing its own
`NamedSharding`s — graft-lint GL501 flags `Mesh(...)`/`jax.devices()`
construction anywhere else.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes; -1 for one axis means 'all remaining devices'."""

    axes: Dict[str, int]

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) or 1
        if len(wild) > 1:
            raise ValueError("At most one axis may be -1")
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"Mesh axes {sizes} use {total} devices but {n_devices} "
                f"are available")
        return sizes


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. Default: 1-D data-parallel over all devices.

    On multi-host TPU slices, `jax.devices()` is globally ordered so the
    trailing mesh axes land on ICI-adjacent chips — put the
    highest-bandwidth-demand axis (model/seq) LAST, data FIRST so its
    collectives can ride DCN if the mesh spans slices (scaling-book recipe).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {AXIS_DATA: len(devices)}
    sizes = MeshSpec(dict(axes)).resolve(len(devices))
    names = tuple(sizes)
    shape = tuple(sizes[n] for n in names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, names)


class MeshContext:
    """The sharding spine: one mesh × one rule set × every placement.

    Bundles a (possibly multi-axis) `Mesh` with a single `ShardingRules`
    and derives from them ALL the shardings training needs:

      batch       — leading dim over `batch_axis` (data parallel)
      params      — per-leaf from the rules (replicated when no rules)
      optimizer   — moments follow their param's spec when it shards
                    anything (FSDP/tensor parallel); otherwise they are
                    sharded across the REPLICA axis (`batch_axis`) on the
                    first evenly-divisible dim — cross-replica weight-
                    update sharding (arXiv:2004.13336), an ~Nx per-device
                    HBM cut that replicated-moment training wastes.

    Rule precedence for a param leaf: first matching (layer_glob,
    param_glob) rule wins; no match → `rules.default` (replicated).
    Moment leaves inherit the param's resolved spec before the replica-
    axis fallback applies. `shard_opt_state=False` is the escape hatch
    back to fully-replicated optimizer state.

    Construct these HERE (or let `ParallelWrapper` do it); the active
    context is what `DevicePrefetchIterator` and the fused-update policy
    consult, installed for the duration of a fit by `use_mesh_context`.
    """

    def __init__(self, mesh: Optional[Mesh] = None, rules=None, *,
                 batch_axis: str = AXIS_DATA,
                 model_axis: str = AXIS_MODEL,
                 shard_opt_state: bool = True):
        self.mesh = mesh if mesh is not None else make_mesh()
        if batch_axis not in self.mesh.axis_names:
            raise ValueError(
                f"Mesh {self.mesh.axis_names} has no {batch_axis!r} axis")
        self.rules = rules
        self.batch_axis = batch_axis
        self.model_axis = model_axis
        self.shard_opt_state = bool(shard_opt_state)
        self.data_size = int(self.mesh.shape[batch_axis])
        self.replicated = NamedSharding(self.mesh, P())

    # ------------------------------------------------------------ batch
    def batch_spec(self, ndim: int) -> P:
        return P(self.batch_axis, *([None] * (ndim - 1)))

    def batch_sharding(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(ndim))

    def batch_sharding_like(self, x):
        """NamedSharding tree for a batch leaf/dict (None passes through)."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self.batch_sharding_like(v) for k, v in x.items()}
        return self.batch_sharding(x.ndim)

    def put_batch(self, x):
        """ONE device_put landing a host batch pre-sharded over the batch
        axis. Leaves whose leading dim does not divide the axis fall back
        to a plain (unsharded) put — callers that pad (ParallelWrapper)
        never hit the fallback."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self.put_batch(v) for k, v in x.items()}
        nd = getattr(x, "ndim", 0)
        if nd >= 1 and x.shape[0] % self.data_size == 0 and x.shape[0] > 0:
            return jax.device_put(x, self.batch_sharding(nd))
        return jax.device_put(x)

    # ----------------------------------------------------------- params
    def _param_spec(self, layer_name: str, param_name: str, leaf) -> P:
        if self.rules is None:
            return P()
        spec = self.rules.spec_for(layer_name, param_name)
        nd = getattr(leaf, "ndim", None)
        if nd is not None and len(spec) > nd:
            spec = P()
        return spec

    def param_shardings(self, tree):
        """NamedSharding tree matching a {layer: {param: leaf}} tree.
        Param-name rules apply at the LEAF key, so nested structures keep
        working."""
        return self._tree_shardings(tree, self._param_spec)

    def state_shardings(self, tree):
        """Layer running state (batch-norm stats, ...) stays replicated."""
        return jax.tree_util.tree_map(lambda _: self.replicated, tree)

    # -------------------------------------------------- optimizer state
    def moment_spec(self, layer_name: str, param_name: str, leaf) -> P:
        """Spec for one optimizer-moment leaf (shaped like its param)."""
        spec = self._param_spec(layer_name, param_name, leaf)
        if any(a is not None for a in spec):
            return spec                 # FSDP/TP: moments follow the param
        if not self.shard_opt_state or self.data_size <= 1:
            return P()
        shape = getattr(leaf, "shape", ())
        for i, d in enumerate(shape):
            if d > 0 and d % self.data_size == 0:
                return P(*([None] * i), self.batch_axis)
        return P()                      # too small to split evenly

    def opt_shardings(self, tree, moment_keys=None):
        """NamedSharding tree for an updater-state tree
        ({layer: {"m": {param: leaf}, ...}} or {layer: ()}). Leaves under
        a state key in `moment_keys` (default: every param-shaped moment
        key any built-in updater declares) get `moment_spec`; anything
        else replicates."""
        if moment_keys is None:
            from deeplearning4j_tpu.optim.updaters import MOMENT_STATE_KEYS
            moment_keys = MOMENT_STATE_KEYS

        def spec_fn(layer_name, param_name, leaf, _state_key=None):
            if _state_key is not None and _state_key in moment_keys:
                return self.moment_spec(layer_name, param_name, leaf)
            return self._param_spec(layer_name, param_name, leaf)

        return self._tree_shardings(tree, spec_fn, state_keyed=True)

    # ---------------------------------------------------------- helpers
    def _tree_shardings(self, tree, spec_fn, *, state_keyed: bool = False):
        """Walk {layer: subtree}; rules apply at the LEAF key (so updater
        state like {'m': {'W': ...}} resolves against param 'W'), with the
        top-level state key ('m', 'v', ...) threaded through when
        `state_keyed` so moments can diverge from their param's spec."""
        def build(layer_name, sub, state_key=None):
            if not isinstance(sub, dict):
                return jax.tree_util.tree_map(
                    lambda _: self.replicated, sub)
            out = {}
            for k, v in sub.items():
                if isinstance(v, dict):
                    sk = k if state_keyed and state_key is None else state_key
                    out[k] = build(layer_name, v, sk)
                else:
                    spec = (spec_fn(layer_name, k, v) if state_key is None
                            else spec_fn(layer_name, k, v, state_key))
                    out[k] = NamedSharding(self.mesh, spec)
            return out

        return {ln: build(ln, sub) for ln, sub in tree.items()}


# The active spine. A process normally has exactly ONE MeshContext (the
# ROADMAP's "one mesh for data x model x optimizer-state parallelism");
# the thread-local stack exists so concurrent fits (serving + training
# in one process) cannot see each other's mesh mid-trace.
_SPINE_TLS = threading.local()
_SPINE_DEFAULT: Optional[MeshContext] = None


def set_mesh_context(ctx: Optional[MeshContext]) -> Optional[MeshContext]:
    """Install `ctx` as the process-wide default spine; returns the
    previous default (restore it when done)."""
    global _SPINE_DEFAULT
    prev, _SPINE_DEFAULT = _SPINE_DEFAULT, ctx
    return prev


def current_mesh_context() -> Optional[MeshContext]:
    """The innermost `use_mesh_context` on this thread, else the
    process default, else None (single-device semantics everywhere)."""
    stack = getattr(_SPINE_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _SPINE_DEFAULT


@contextlib.contextmanager
def use_mesh_context(ctx: Optional[MeshContext]):
    """Scope `ctx` as the active spine for this thread (trainers wrap
    their dispatch loops in this so batch placement and trace-time
    policies agree on the mesh)."""
    stack = getattr(_SPINE_TLS, "stack", None)
    if stack is None:
        stack = _SPINE_TLS.stack = []
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def shard_map_compat(fn, mesh, in_specs, out_specs, *, check: bool = False):
    """One shard_map entry point across jax versions: new-API
    `jax.shard_map` (check_vma) or the old experimental import
    (check_rep). Every shard_map call site in the package routes
    through here so an API change is a one-line fix. `check=True`
    keeps jax's default replication/vma checking (pipeline's psum-
    reduced outputs pass it); False disables it (ring attention's
    merged partials do not)."""
    try:
        from jax import shard_map
        kw = {} if check else {"check_vma": False}
    except ImportError:                      # older jax
        from jax.experimental.shard_map import shard_map
        kw = {} if check else {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)
