"""Elastic training: preemption handling + the pod-level outer driver.

SURVEY §5 ("Failure detection / elastic recovery: no elastic training" in
the reference — "TPU build should do better: checkpoint-restart +
preemption handling") and the layer-5 outer-driver role the reference
delegates to Spark (SURVEY §2.8 item 5: "Spark as the multi-node
scheduler ↦ JAX multi-controller / GCE orchestration as outer driver").

Pieces:
- PreemptionHandler: installs signal handlers (SIGTERM — what TPU VM
  maintenance events deliver) that set a flag checked at step
  boundaries; training stops CLEANLY (after the in-flight step and a
  final sharded checkpoint) instead of dying mid-write.
- ElasticTrainer: the outer driver loop — initialize distributed (when
  configured), wrap the model for the mesh, auto-resume from the newest
  committed checkpoint, train with periodic async sharded checkpoints,
  and on preemption checkpoint + return resumable=True. Re-running the
  same program continues the loss curve exactly (the guarantee tested in
  tests/test_sharded_checkpoint.py, now reachable without manual
  restore calls).
"""

from __future__ import annotations

import logging
import signal
from typing import Any, Callable, Dict, Optional, Sequence

from deeplearning4j_tpu.parallel.checkpoint import ShardedCheckpointer
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

logger = logging.getLogger("deeplearning4j_tpu")

_warned_off_main_thread = False


class PreemptionHandler:
    """Flag-setting signal handler (reference precedent: none — the
    reference has no preemption story; ParallelWrapper.java:94-99 only
    installs an UncaughtExceptionHandler)."""

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self._preempted = False
        self._previous: Dict[int, Any] = {}
        self.signals = tuple(signals)
        # True when install() could not register handlers (non-main
        # thread); preemption then only arrives via request_stop()/stop_fn
        self.degraded = False

    def install(self) -> "PreemptionHandler":
        global _warned_off_main_thread
        for s in self.signals:
            try:
                self._previous[s] = signal.signal(s, self._on_signal)
            except ValueError:
                # signal.signal is main-thread-only; under threaded test
                # runners / servers the fit must still run — degrade to
                # the stop_fn/request_stop path instead of crashing
                self.degraded = True
                if not _warned_off_main_thread:
                    _warned_off_main_thread = True
                    logger.warning(
                        "PreemptionHandler.install(): not on the main "
                        "thread, signal handlers unavailable — relying on "
                        "stop_fn/request_stop() for preemption (warning "
                        "once per process)")
        return self

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()

    def _on_signal(self, signum, frame):
        self._preempted = True

    def request_stop(self) -> None:
        """Programmatic preemption — the delivery path that still works
        when install() degraded off the main thread."""
        self._preempted = True

    @property
    def preempted(self) -> bool:
        return self._preempted

    def reset(self) -> None:
        self._preempted = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *a):
        self.uninstall()


class ElasticTrainer:
    """Preemption-safe outer training driver over ParallelWrapper +
    ShardedCheckpointer.

    fit() returns a dict: {"completed": bool, "preempted": bool,
    "iteration": int} — a preempted run checkpoints and returns; running
    the same fit() again (same directory) resumes mid-epoch and finishes
    the remaining epochs with a bit-identical loss curve."""

    def __init__(self, net, checkpoint_dir: str, *, mesh=None,
                 param_rules=None, checkpoint_every: int = 10,
                 max_to_keep: int = 3,
                 preemption_signals: Sequence[int] = (signal.SIGTERM,),
                 stop_fn: Optional[Callable[[], bool]] = None,
                 spine=None, shard_opt_state: bool = True):
        # the spine survives preemption cycles: a restart on a SMALLER
        # mesh builds a fresh context here and restore_into_wrapper
        # re-partitions params AND replica-sharded moments onto it
        self.wrapper = ParallelWrapper(net, mesh=mesh,
                                       param_rules=param_rules,
                                       spine=spine,
                                       shard_opt_state=shard_opt_state)
        self.checkpointer = ShardedCheckpointer(
            checkpoint_dir, max_to_keep=max_to_keep)
        self.checkpoint_every = checkpoint_every
        self.handler = PreemptionHandler(preemption_signals)
        self._extra_stop = stop_fn

    def _should_stop(self) -> bool:
        if self.handler.preempted:
            return True
        return bool(self._extra_stop and self._extra_stop())

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> Dict[str, Any]:
        net = self.wrapper.net
        resume = None
        if self.checkpointer.latest_step() is not None:
            resume = self.checkpointer.restore_into_wrapper(self.wrapper)
        with self.handler:
            # the wrapper's RecoveryPlan owns the rest: periodic async
            # saves, the final exact-position snapshot on stop, and the
            # writer flush (finalize) — this driver just supplies the
            # handler-aware stop predicate and reports the outcome
            self.wrapper.fit(
                data, labels, epochs=epochs, batch_size=batch_size,
                checkpointer=self.checkpointer,
                checkpoint_every=self.checkpoint_every,
                resume=resume, stop_fn=self._should_stop)
            # the wrapper's record is authoritative — a transient stop_fn
            # that flipped back must still report the truncated run
            preempted = self.wrapper.stopped_early
        return {"completed": not preempted, "preempted": preempted,
                "iteration": net.iteration}
