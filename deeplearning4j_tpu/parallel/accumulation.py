"""Quantized gradient exchange — GradientsAccumulator equivalent.

Mirrors the reference's SHARED_GRADIENTS machinery
(deeplearning4j-nn/.../optimize/solvers/accumulation/: GradientsAccumulator,
BasicGradientsAccumulator, EncodingHandler.java:26-102 threshold encoding,
LocalHandler; consumed by ParallelWrapper SHARED_GRADIENTS mode,
ParallelWrapper.java:61-63, SymmetricTrainer.java:82-84).

On TPU the intra-slice path needs none of this — data-parallel gradient
exchange is an XLA psum over ICI inside the jitted step. What this module
keeps is the ASYNC, bandwidth-compressed exchange pattern for where it still
pays: host↔host traffic over DCN (parameter-server-style training across
slices). Encoding is the native C++ threshold codec
(deeplearning4j_tpu.native.threshold_encode); transport is pluggable via
MessageHandler, defaulting to in-process LocalHandler.
"""

from __future__ import annotations

import queue
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu import native


class MessageHandler:
    """Transport SPI (reference MessageHandler.java): broadcast an encoded
    update to peers; deliver received updates into the accumulator."""

    accumulator: Optional["GradientsAccumulator"] = None

    def initialize(self, accumulator: "GradientsAccumulator") -> None:
        self.accumulator = accumulator

    def broadcast(self, packed: np.ndarray, threshold: float, n: int) -> None:
        raise NotImplementedError


class LocalHandler(MessageHandler):
    """In-process loopback (reference LocalHandler.java) — peers share one
    accumulator; used by tests and single-host multi-replica training."""

    def broadcast(self, packed, threshold, n):
        if self.accumulator is not None:
            self.accumulator.receive_update(packed, threshold, n)


class EncodingHandler:
    """Threshold-encodes a dense gradient into a sparse 1-bit message
    (reference EncodingHandler.java:57-102).

    The residual below the threshold stays in ``residual`` and is carried
    into later rounds, so no gradient mass is dropped, only delayed.
    """

    def __init__(self, threshold: float = 1e-3,
                 handler: Optional[MessageHandler] = None):
        self.threshold = float(threshold)
        self.handler = handler or LocalHandler()
        self.residual: Optional[np.ndarray] = None

    def broadcast_update(self, gradient: np.ndarray) -> int:
        """Accumulate gradient into the residual, encode everything above
        threshold, broadcast. Returns number of encoded elements."""
        flat = np.asarray(gradient, dtype=np.float32).reshape(-1)
        if self.residual is None:
            self.residual = np.zeros_like(flat)
        self.residual += flat
        idx, signs = native.threshold_encode(self.residual, self.threshold)
        if idx.size:
            packed = (idx.astype(np.int64) * 2 + signs).astype(np.int64)
            self.handler.broadcast(packed, self.threshold, flat.size)
        return int(idx.size)


def _unpack(packed: np.ndarray):
    idx = (packed // 2).astype(np.int32)
    signs = (packed % 2).astype(np.uint8)
    return idx, signs


class GradientsAccumulator:
    """Receives encoded peer updates and applies them to local params.

    Reference contract (GradientsAccumulator.java): workers call
    ``store_update`` (via EncodingHandler.broadcast) after each step and
    ``apply_update`` before their next step, folding peers' quantized
    gradients into their own view — allreduce-by-gossip without a barrier.
    """

    def __init__(self, n_params: int):
        self.n_params = int(n_params)
        self._queue: "queue.Queue" = queue.Queue()

    def receive_update(self, packed: np.ndarray, threshold: float,
                       n: int) -> None:
        if n != self.n_params:
            raise ValueError(
                f"update for {n} params, accumulator holds {self.n_params}")
        self._queue.put((packed, float(threshold)))

    def apply_updates(self, target: np.ndarray,
                      scale: float = 1.0) -> int:
        """Drains pending updates into ``target`` (flat float32, in place).
        Returns how many messages were applied."""
        if (not isinstance(target, np.ndarray)
                or target.dtype != np.float32
                or not target.flags["C_CONTIGUOUS"]):
            # reshape(-1) on a non-contiguous view would copy, and the
            # decode would land in the throwaway copy — reject instead.
            raise ValueError("target must be a C-contiguous float32 array")
        applied = 0
        flat = target.reshape(-1)
        while True:
            try:
                packed, threshold = self._queue.get_nowait()
            except queue.Empty:
                return applied
            idx, signs = _unpack(packed)
            native.threshold_decode(flat, threshold * scale, idx, signs)
            applied += 1

    @property
    def pending(self) -> int:
        return self._queue.qsize()


class SharedGradientsExchange:
    """N local workers exchanging threshold-quantized updates — the moral
    equivalent of ParallelWrapper SHARED_GRADIENTS wiring
    (SymmetricTrainer.java:82-84): every worker's broadcast lands in every
    OTHER worker's accumulator."""

    def __init__(self, n_workers: int, n_params: int,
                 threshold: float = 1e-3):
        self.accumulators = [GradientsAccumulator(n_params)
                             for _ in range(n_workers)]
        self.handlers: List[EncodingHandler] = []
        for w in range(n_workers):
            exchange = self

            class _Fanout(MessageHandler):
                def __init__(self, src: int):
                    self.src = src

                def broadcast(self, packed, threshold, n):
                    for j, acc in enumerate(exchange.accumulators):
                        if j != self.src:
                            acc.receive_update(packed, threshold, n)

            self.handlers.append(
                EncodingHandler(threshold, handler=_Fanout(w)))

    def publish(self, worker: int, gradient: np.ndarray) -> int:
        return self.handlers[worker].broadcast_update(gradient)

    def collect(self, worker: int, target: np.ndarray) -> int:
        return self.accumulators[worker].apply_updates(target)
