"""Mixture-of-Experts with expert parallelism over the `expert` mesh axis.

No reference counterpart: DL4J has no conditional-compute layers (SURVEY
§2.4/§5 — parallelism surface is data-parallel only); this is a green-field
TPU-scale extension required by SURVEY §7 step 7.

TPU-first design (GShard/Switch-style, MXU-friendly):
- Routing is expressed entirely as dense one-hot einsums over a FIXED
  per-expert capacity C — no dynamic shapes, no gather/scatter loops, so XLA
  tiles everything onto the MXU and the dispatch/combine contractions lower
  to all_to_all over ICI when the expert axis of the parameter leaves is
  sharded over the `expert` mesh axis (collectives are inserted by the
  partitioner from sharding constraints — the scaling-book recipe — rather
  than hand-written).
- Load balancing uses the standard auxiliary loss (mean gate fraction ×
  mean routed fraction, scaled by E); the layer reports it through the
  state pytree under "aux_loss" and the model runtimes add it to the score
  inside the differentiated loss closure.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.parallel.mesh import AXIS_EXPERT


_ACTIVE_MESH: List[Tuple[Mesh, str]] = []


@contextlib.contextmanager
def expert_mesh(mesh: Mesh, axis: str = AXIS_EXPERT):
    """Make `mesh` visible to MoEFeedForward layers traced inside the block.

    The layer API has no mesh parameter (layers are mesh-agnostic pure
    functions), so the sharding constraints that pin dispatch/combine to
    all_to_all need a side channel. Activate this context around the call
    that TRACES the train/inference step (fit(), make_step_fn() + jit, ...);
    the constraint is baked into the jaxpr at trace time.
    """
    _ACTIVE_MESH.append((mesh, axis))
    try:
        yield
    finally:
        _ACTIVE_MESH.pop()


def _active_expert_mesh() -> Tuple[Optional[Mesh], str]:
    return _ACTIVE_MESH[-1] if _ACTIVE_MESH else (None, AXIS_EXPERT)


def top_k_gating(logits, k: int, capacity: int, token_mask=None):
    """Top-k token→expert routing with fixed expert capacity.

    logits: [N, E]. Returns (combine [N, E, C], dispatch [N, E, C],
    aux_loss scalar). Tokens overflowing an expert's capacity are dropped
    (their combine weights are zero — residual connections carry them).
    token_mask: optional [N] 0/1 — masked (padding) tokens are excluded from
    routing entirely: they occupy no capacity and don't skew the aux loss.
    """
    n, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    if token_mask is not None:
        probs = probs * token_mask[:, None].astype(probs.dtype)
    denom = (jnp.maximum(jnp.sum(token_mask.astype(probs.dtype)), 1.0)
             if token_mask is not None else jnp.asarray(float(n), probs.dtype))
    combine = jnp.zeros((n, e, capacity), probs.dtype)
    dispatch = jnp.zeros((n, e, capacity), jnp.bool_)
    masked = probs
    # Occupancy accumulates across the k rounds so slot indices never collide.
    occupancy = jnp.zeros((e,), jnp.int32)
    fraction_routed = jnp.zeros((e,), probs.dtype)
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)                     # [N]
        onehot_raw = jax.nn.one_hot(choice, e, dtype=jnp.int32)   # [N, E]
        # A token whose remaining probs are all zero (padding, or E < k) is
        # out of the round: no capacity slot, no routed-fraction credit.
        valid = jnp.max(masked, axis=-1) > 0                      # [N]
        onehot = onehot_raw * valid[:, None].astype(jnp.int32)
        pos = occupancy[None, :] + jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum(pos * onehot, axis=-1)                      # [N]
        keep = (pos < capacity) & valid
        occupancy = occupancy + jnp.sum(
            onehot * keep[:, None].astype(jnp.int32), axis=0)
        slot = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)   # [N, C]
        gate = jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
        route = (onehot.astype(probs.dtype) * keep[:, None]
                 )[:, :, None] * slot[:, None, :]                 # [N, E, C]
        combine = combine + gate[:, None, None] * route
        dispatch = dispatch | (route > 0)
        fraction_routed = fraction_routed + jnp.sum(
            onehot.astype(probs.dtype), axis=0) / denom
        masked = masked * (1.0 - onehot_raw.astype(probs.dtype))
    # Switch-transformer load-balance loss: E * <p_e> . <f_e> (per round,
    # averaged, over VALID tokens); pushes toward uniform expert utilisation.
    aux = e * jnp.sum(jnp.sum(probs, axis=0) / denom * fraction_routed / k)
    return combine, dispatch.astype(probs.dtype), aux


def moe_ffn(params: Dict[str, jax.Array], x, *, k: int = 2,
            capacity_factor: float = 1.25,
            activation: str = "gelu",
            mesh: Optional[Mesh] = None,
            axis: str = AXIS_EXPERT,
            token_mask=None,
            group_size: Optional[int] = None
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Expert-parallel feed-forward over tokens x: [N, d] -> [N, d].

    params: gate [d, E], w1 [E, d, h], b1 [E, h], w2 [E, h, d], b2 [E, d].
    token_mask: optional [N] 0/1 validity (padding excluded from routing).

    group_size=None routes all N tokens in one group — dispatch/combine are
    [N, E, C] with C = cf*k*N/E, i.e. O(N^2) memory; fine for small batches.
    group_size=S switches to GShard-style grouped dispatch ([G, S, E, C],
    C = cf*k*S/E): per-group capacity, memory linear in N, and the G (token)
    → E (expert) resharding of the dispatch einsum lowers to all_to_all over
    ICI when `mesh` is active. Use this at >4k-token scale.

    Returns (y, aux_loss, overflow_frac) — overflow_frac is the fraction of
    desired (token, expert) routes dropped because expert capacity filled up.
    """
    e = params["w1"].shape[0]
    n = x.shape[0]
    act = Activation.get(activation)

    if group_size is None or group_size >= n:
        capacity = max(1, int(capacity_factor * k * n / e))
        logits = x @ params["gate"].astype(x.dtype)
        combine, dispatch, aux = top_k_gating(
            logits.astype(jnp.float32), k, capacity, token_mask=token_mask)
        combine = combine.astype(x.dtype)
        dispatch = dispatch.astype(x.dtype)
        n_valid = (jnp.sum(token_mask) if token_mask is not None
                   else jnp.asarray(float(n), jnp.float32))

        ex_in = jnp.einsum("nec,nd->ecd", dispatch, x)
        if mesh is not None and axis in mesh.axis_names:
            # Pin the expert dim so the partitioner materialises the dispatch
            # as an all_to_all over ICI instead of replicating expert blocks.
            ex_in = jax.lax.with_sharding_constraint(
                ex_in, NamedSharding(mesh, P(axis)))
        h = act(jnp.einsum("ecd,edh->ech", ex_in, params["w1"])
                + params["b1"][:, None, :])
        ex_out = (jnp.einsum("ech,ehd->ecd", h, params["w2"])
                  + params["b2"][:, None, :])
        if mesh is not None and axis in mesh.axis_names:
            ex_out = jax.lax.with_sharding_constraint(
                ex_out, NamedSharding(mesh, P(axis)))
        y = jnp.einsum("nec,ecd->nd", combine, ex_out)
        routed = jnp.sum(dispatch)
    else:
        s = int(group_size)
        pad = (-n) % s
        if pad:
            x_p = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:],
                                                x.dtype)])
            tm = (jnp.concatenate([token_mask.astype(jnp.float32),
                                   jnp.zeros((pad,), jnp.float32)])
                  if token_mask is not None
                  else jnp.concatenate([jnp.ones((n,), jnp.float32),
                                        jnp.zeros((pad,), jnp.float32)]))
        else:
            x_p = x
            tm = (token_mask.astype(jnp.float32)
                  if token_mask is not None else None)
        g = x_p.shape[0] // s
        capacity = max(1, int(capacity_factor * k * s / e))
        x_g = x_p.reshape(g, s, -1)
        if mesh is not None and axis in mesh.axis_names:
            # Token groups data-parallel over the expert devices: the G→E
            # resharding in the dispatch einsum becomes the MoE all_to_all.
            x_g = jax.lax.with_sharding_constraint(
                x_g, NamedSharding(mesh, P(axis)))
        logits_g = (x_g @ params["gate"].astype(x.dtype)).astype(jnp.float32)
        if tm is not None:
            tm_g = tm.reshape(g, s)
            combine, dispatch, aux_g = jax.vmap(
                lambda lg, mg: top_k_gating(lg, k, capacity, token_mask=mg)
            )(logits_g, tm_g)
            n_valid = jnp.sum(tm)
            # Weight by per-group valid tokens: fully-masked groups report
            # aux=0 and must not dilute the load-balance gradient.
            valid_g = jnp.sum(tm_g, axis=1)
            aux = (jnp.sum(aux_g * valid_g)
                   / jnp.maximum(jnp.sum(valid_g), 1.0))
        else:
            combine, dispatch, aux_g = jax.vmap(
                lambda lg: top_k_gating(lg, k, capacity))(logits_g)
            n_valid = jnp.asarray(float(n), jnp.float32)
            aux = jnp.mean(aux_g)
        combine = combine.astype(x.dtype)
        dispatch = dispatch.astype(x.dtype)

        ex_in = jnp.einsum("gsec,gsd->egcd", dispatch, x_g)
        if mesh is not None and axis in mesh.axis_names:
            ex_in = jax.lax.with_sharding_constraint(
                ex_in, NamedSharding(mesh, P(axis)))
        h = act(jnp.einsum("egcd,edh->egch", ex_in, params["w1"])
                + params["b1"][:, None, None, :])
        ex_out = (jnp.einsum("egch,ehd->egcd", h, params["w2"])
                  + params["b2"][:, None, None, :])
        if mesh is not None and axis in mesh.axis_names:
            ex_out = jax.lax.with_sharding_constraint(
                ex_out, NamedSharding(mesh, P(axis)))
        y_g = jnp.einsum("gsec,egcd->gsd", combine, ex_out)
        if mesh is not None and axis in mesh.axis_names:
            y_g = jax.lax.with_sharding_constraint(
                y_g, NamedSharding(mesh, P(axis)))
        y = y_g.reshape(g * s, -1)[:n]
        routed = jnp.sum(dispatch)

    expected = jnp.maximum(n_valid * min(k, e), 1.0)
    overflow = jnp.maximum(0.0, 1.0 - routed / expected)
    return y, aux, overflow


def expert_sharding(params: Dict[str, Any], mesh: Mesh,
                    axis: str = AXIS_EXPERT):
    """NamedShardings: expert-indexed leaves sharded on their E axis, gate
    replicated."""
    return {
        k: NamedSharding(mesh, P() if k == "gate" else P(axis))
        for k in params
    }


@register_layer
@dataclasses.dataclass(frozen=True)
class MoEFeedForward(Layer):
    """Mixture-of-experts FFN layer (d -> d, residual inside).

    Pluggable into MultiLayerNetwork/ComputationGraph like any layer;
    reports its load-balancing auxiliary loss via state["aux_loss"], which
    the model loss closures fold into the score (weighted by aux_weight).
    Accepts [B, d] or RNN-format [B, T, d] activations.
    """

    # Consumes [B, d] or [B, T, d] natively — keep the config builder from
    # inserting an Rnn->FF (last-timestep) preprocessor in front of it.
    CONSUMES = "any"

    n_in: Optional[int] = None
    n_experts: int = 8
    hidden_mult: int = 4
    k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    residual: bool = True
    # GShard-style grouped dispatch: None = single group (fine for small
    # batches); set to e.g. 512-1024 at >4k-token scale to keep the
    # dispatch/combine tensors linear in token count.
    group_size: Optional[int] = None

    def infer_n_in(self, input_type: InputType) -> "MoEFeedForward":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def init_params(self, key, input_type, dtype=jnp.float32):
        d = self.n_in or input_type.size
        h = self.hidden_mult * d
        e = self.n_experts
        ks = jax.random.split(key, 3)
        winit = self._winit()
        params = {
            "gate": winit(ks[0], (d, e), dtype),
            "w1": jnp.stack([winit(jax.random.fold_in(ks[1], i), (d, h), dtype)
                             for i in range(e)]),
            "b1": jnp.zeros((e, h), dtype),
            "w2": jnp.stack([winit(jax.random.fold_in(ks[2], i), (h, d), dtype)
                             for i in range(e)]),
            "b2": jnp.zeros((e, d), dtype),
        }
        # Non-empty init state marks the layer stateful, so the model
        # runtimes persist the per-step routing metrics into state_tree —
        # net.state_tree[name]["overflow_frac"] is user-visible after fit.
        state = {"aux_loss": jnp.zeros((), jnp.float32),
                 "overflow_frac": jnp.zeros((), jnp.float32)}
        return params, state

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None):
        x = self._maybe_dropout(x, train, rng)
        rnn = x.ndim == 3
        token_mask = None
        if rnn:  # [B, T, d] (framework RNN layout, recurrent.py) -> [B*T, d]
            b, t, d = x.shape
            tokens = x.reshape(b * t, d)
            if mask is not None:  # [B, T] timestep mask -> [B*T]
                token_mask = jnp.reshape(mask, (b * t,))
        else:
            tokens = x
        mesh, axis = _active_expert_mesh()
        y, aux, overflow = moe_ffn(
            params, tokens, k=self.k,
            capacity_factor=self.capacity_factor,
            activation=self.activation or "gelu",
            mesh=mesh, axis=axis, token_mask=token_mask,
            group_size=self.group_size)
        if self.residual:
            y = y + tokens
        if rnn:
            y = y.reshape(b, t, d)
        return y, {"aux_loss": self.aux_weight * aux,
                   "overflow_frac": overflow}
