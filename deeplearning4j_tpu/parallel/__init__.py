"""Parallelism over TPU device meshes.

Reference parity (redesigned): deeplearning4j-scaleout's five data-parallel
flavors (SURVEY §2.4) — ParallelWrapper AVERAGING / SHARED_GRADIENTS, Spark
parameter averaging, Aeron parameter server, hogwild embeddings — all
collapse on TPU into sharded jit over a `jax.sharding.Mesh` with XLA
collectives over ICI (allreduce replaces quantized-gradient queues,
treeAggregate, and the PS daemon at once; SURVEY §5 'distributed
communication backend').

Extensions beyond the reference (required for TPU scale, SURVEY §7 step 7):
tensor/sequence parallelism as extra mesh axes, ring attention for long
context, multi-host DCN initialization.
"""

from deeplearning4j_tpu.parallel.mesh import (
    MeshContext, MeshSpec, current_mesh_context, device_count,
    local_device_count, make_mesh, set_mesh_context, use_mesh_context,
)
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.sharding import (
    ShardingRules, shard_params, replicate, batch_sharding,
    fsdp_rules, tensor_parallel_rules,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.distributed import initialize_distributed
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallel, PipelinedNetwork, make_pipeline_fn,
    make_pipeline_1f1b_fn, partition_for_pipeline, stack_stage_params,
    split_microbatches,
)
from deeplearning4j_tpu.parallel.moe import (
    MoEFeedForward, moe_ffn, top_k_gating, expert_sharding, expert_mesh,
)
from deeplearning4j_tpu.parallel.training_master import (
    TrainingMaster, ParameterAveragingTrainingMaster,
    DistributedTrainingMaster, PhaseStats, distributed_evaluate,
    export_timeline_html,
)
from deeplearning4j_tpu.parallel.estimator import NetworkEstimator
from deeplearning4j_tpu.parallel.checkpoint import ShardedCheckpointer
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer, PreemptionHandler
from deeplearning4j_tpu.parallel.async_ps import AsyncParameterServer, AsyncTrainer
from deeplearning4j_tpu.parallel.chaos import (
    CheckpointIOFault, FailingIterator, InjectedFault, SigtermAtStep,
    StallingIterator,
)

__all__ = [
    "ShardedCheckpointer", "ElasticTrainer", "PreemptionHandler",
    "CheckpointIOFault", "FailingIterator", "InjectedFault", "SigtermAtStep",
    "StallingIterator",
    "AsyncParameterServer", "AsyncTrainer",
    "MeshContext", "MeshSpec", "current_mesh_context", "set_mesh_context",
    "use_mesh_context",
    "make_mesh", "device_count", "local_device_count",
    "ParallelWrapper", "ParallelInference",
    "ShardingRules", "shard_params", "replicate", "batch_sharding",
    "fsdp_rules", "tensor_parallel_rules", "initialize_distributed",
    "PipelineParallel", "PipelinedNetwork", "make_pipeline_fn",
    "make_pipeline_1f1b_fn", "partition_for_pipeline", "stack_stage_params",
    "split_microbatches",
    "MoEFeedForward", "moe_ffn", "top_k_gating", "expert_sharding",
    "expert_mesh",
    "TrainingMaster", "ParameterAveragingTrainingMaster",
    "DistributedTrainingMaster", "PhaseStats", "NetworkEstimator",
    "distributed_evaluate", "export_timeline_html",
]
