"""ParallelWrapper — sharded-jit multi-device trainer.

Reference parity: `parallelism/ParallelWrapper.java` (SURVEY §3.3): the
reference round-robins minibatches to N replica threads and averages
params/updater state every `averagingFrequency` iterations (AVERAGING mode)
or exchanges threshold-quantized gradients (SHARED_GRADIENTS mode). On TPU
the whole construct is ONE jitted train step over a mesh: the global batch
is sharded over the `data` axis, params are replicated (or FSDP-sharded via
rules), and XLA emits a single fused allreduce over ICI for the gradients —
mathematically the reference's averaging with frequency 1, without
quantization (ICI bandwidth makes 1-bit compression pointless — SURVEY §5).

Works over MultiLayerNetwork and ComputationGraph. Same API shape as the
reference: wrap a model, call fit(iterator).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DevicePrefetchIterator, as_iterator,
)
from deeplearning4j_tpu.observe import donatemon
from deeplearning4j_tpu.optim.executor import TrainingExecutor
from deeplearning4j_tpu.optim.recovery import RecoveryPlan, run_with_recovery
from deeplearning4j_tpu.parallel.distributed import (
    put_global, put_global_batch,
)
from deeplearning4j_tpu.parallel.mesh import (
    AXIS_DATA, MeshContext, make_mesh,
)
from deeplearning4j_tpu.parallel.ring_attention import SeqCtxJitCache
from deeplearning4j_tpu.parallel.sharding import ShardingRules


def _is_graph(net) -> bool:
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph

    return isinstance(net, ComputationGraph)


class ParallelWrapper(SeqCtxJitCache):
    """Data-parallel trainer over a mesh.

    Kwargs mirror the reference Builder (`ParallelWrapper.java:562-715`)
    where meaningful: `prefetch_buffer` maps to async-iterator depth;
    `workers` is implied by the mesh's data-axis size. Gradient averaging is
    exact and per-step (allreduce), i.e. averagingFrequency=1 semantics.
    `param_rules` opts into FSDP/ZeRO-style parameter+optimizer sharding
    (reference precedent: none — extension).

    Placement comes from ONE `parallel.mesh.MeshContext` (the sharding
    spine): pass a prebuilt `spine`, or let the wrapper assemble one from
    `mesh`/`param_rules`/`batch_axis`. By contract the spine shards the
    optimizer moments across the replica axis even when params replicate
    (weight-update sharding, ~data_size× less optimizer HBM per device);
    `shard_opt_state=False` is the escape hatch back to replicated
    moments (see PERF_NOTES — replicating them is a regression)."""

    def __init__(self, net, *, mesh: Optional[Mesh] = None,
                 param_rules: Optional[ShardingRules] = None,
                 prefetch_buffer: int = 2,
                 batch_axis: str = AXIS_DATA,
                 spine: Optional[MeshContext] = None,
                 shard_opt_state: bool = True):
        if net.params_tree is None:
            raise RuntimeError("Model must be init()ed before wrapping")
        if getattr(net.conf, "optimization_algo",
                   "stochastic_gradient_descent") != \
                "stochastic_gradient_descent":
            raise ValueError(
                "ParallelWrapper trains with the sharded SGD step; "
                f"optimization_algo={net.conf.optimization_algo!r} is a "
                "full-batch single-device solver — fit the model directly")
        self.net = net
        if spine is None:
            spine = MeshContext(
                mesh if mesh is not None else make_mesh(),
                param_rules, batch_axis=batch_axis,
                shard_opt_state=shard_opt_state)
        self.spine = spine
        self.mesh = spine.mesh
        self.batch_axis = spine.batch_axis
        self.param_rules = spine.rules
        self.prefetch = prefetch_buffer
        self._graph = _is_graph(net)
        self.last_batch_index = -1   # in-epoch position (elastic resume)
        self.stopped_early = False   # did the last fit() stop via stop_fn?

        self.data_size = spine.data_size
        # Multi-controller: each process feeds a host-LOCAL slice of every
        # batch; padding must make the local slice divide the local devices.
        self._nproc = jax.process_count()
        self._local_divisor = max(1, self.data_size // self._nproc)

        self._rep = spine.replicated
        self._params_sh = spine.param_shardings(net.params_tree)
        self._opt_sh = spine.opt_shardings(
            net.updater_state, self._moment_keys())
        net.params_tree = jax.tree_util.tree_map(
            put_global, net.params_tree, self._params_sh)
        net.updater_state = jax.tree_util.tree_map(
            put_global, net.updater_state, self._opt_sh)
        if net.state_tree:
            net.state_tree = jax.tree_util.tree_map(
                lambda x: put_global(x, self._rep), net.state_tree)

    # ------------------------------------------------------- shardings
    def _moment_keys(self):
        """State keys the spine may replica-shard: what this net's actual
        updaters declare, or every built-in moment key as the fallback."""
        ups = getattr(self.net, "_layer_updaters", None)
        if not ups:
            return None
        return frozenset(k for u in ups.values()
                         for k in getattr(u, "sharded_state", ()))

    def _param_tree_sharding(self, tree):
        """NamedSharding tree matching `tree`'s structure (spine rules at
        the leaf key). Kept as the wrapper-level seam; placement itself
        lives in `MeshContext`."""
        return self.spine.param_shardings(tree)

    def _batch_sharding_like(self, x):
        return self.spine.batch_sharding_like(x)

    # ------------------------------------------------------- step build
    def _get_step(self, key, example_args):
        if key in self._jit_cache:
            return self._jit_cache[key]
        base = self.net.make_step_fn()
        if self._graph:
            # (params, opt, states, step, inputs, labels, fmasks, lmasks, rng)
            _, _, _, _, feats, labs, fms, lms, _ = example_args
            in_sh = (self._params_sh, self._opt_sh, self._rep, self._rep,
                     self._batch_sharding_like(feats),
                     self._batch_sharding_like(labs),
                     self._batch_sharding_like(fms),
                     self._batch_sharding_like(lms),
                     self._rep)
            # (params, opt, states, loss)
            out_sh = (self._params_sh, self._opt_sh, self._rep, self._rep)
        else:
            # (params, opt, states, step, feats, labels, fm, lm, rng, carries)
            _, _, _, _, feats, labs, fm, lm, _, _ = example_args
            in_sh = (self._params_sh, self._opt_sh, self._rep, self._rep,
                     self._batch_sharding_like(feats),
                     self._batch_sharding_like(labs),
                     self._batch_sharding_like(fm),
                     self._batch_sharding_like(lm),
                     self._rep, None)
            # (params, opt, persist, loss, carries)
            out_sh = (self._params_sh, self._opt_sh, self._rep, self._rep,
                      None)
        # out_shardings pin the donated params/opt buffers to their input
        # placement — the moments stay replica-sharded through the update
        # instead of silently re-replicating (the regression the perf
        # gate's opt_state_shard_factor budget exists to catch).
        fn = donatemon.instrument(
            jax.jit(base, in_shardings=in_sh, out_shardings=out_sh,
                    donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="ParallelWrapper._step",
            arg_names=("params", "opt_state", "states"))
        self._jit_cache[key] = fn
        # read back through the cache: __setitem__ may have wrapped the
        # callable in the watchdog's cost/comm probe, and returning the
        # raw local would let the FIRST dispatch (often the only one in
        # a short fit) bypass the ledger entirely
        return self._jit_cache[key]

    # -------------------------------------------------------------- fit
    def _pad_to_divisible(self, ds):
        div = self._local_divisor if self._nproc > 1 else self.data_size
        b = ds.num_examples()
        if b % div == 0:
            return ds
        pad = div - (b % div)
        idx = np.concatenate([np.arange(b), np.zeros(pad, np.int64)])
        if isinstance(ds, MultiDataSet):
            return MultiDataSet(
                [f[idx] for f in ds.features], [l[idx] for l in ds.labels],
                None if not ds.features_masks else
                [None if m is None else m[idx] for m in ds.features_masks],
                None if not ds.labels_masks else
                [None if m is None else m[idx] for m in ds.labels_masks])
        sl = lambda a: None if a is None else a[idx]
        return DataSet(ds.features[idx], sl(ds.labels),
                       sl(ds.features_mask), sl(ds.labels_mask))

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128, checkpointer=None,
            checkpoint_every: int = 1, resume=None,
            stop_fn=None, preemption=None, steps_per_dispatch: int = 1,
            device_prefetch: bool = True, sync_every: int = 0):
        """Reference: `ParallelWrapper.fit(DataSetIterator):409`. Partial
        final batches are padded by repetition to keep XLA shapes static.

        Multi-controller (jax.process_count() > 1): `data` and
        `batch_size` are PER-PROCESS — each controller feeds its host-local
        slice and the global batch is their concatenation in process order
        (global batch = batch_size * process_count). Pass GLOBAL sizes to
        DistributedTrainingMaster.execute_training instead, which shards
        and divides for you.

        Recovery (shared `optim/recovery.RecoveryPlan` — same semantics as
        `MultiLayerNetwork.fit`): `checkpointer` (a ShardedCheckpointer)
        saves sharded snapshots every `checkpoint_every` iterations, async.
        `resume` takes the position dict returned by
        `ShardedCheckpointer.restore_into_wrapper`, or `"auto"` to restore
        the newest committed step with this wrapper's shardings — training
        continues mid-epoch from the exact batch/rng/step, and `epochs`
        counts TOTAL epochs over the whole (resumed) run so an interrupted
        fit(epochs=N) is finished by the same call. `stop_fn` /
        `preemption=True` end training cleanly at a batch boundary — the
        preemption seam used by ElasticTrainer.

        Async-dispatch knobs (see MultiLayerNetwork.fit / PERF_NOTES):
        `device_prefetch` pre-shards batch N+1 across the mesh while batch
        N computes (single-controller only — multi-controller feeding goes
        through `put_global_batch`); `steps_per_dispatch=K` fuses K batches
        into one `lax.scan` dispatch. Fusion now COMPOSES with recovery:
        checkpoints land at scan-window boundaries (where params are
        consistent) and a resume replays into a partial window per-step."""
        net = self.net

        def prepare(ds):
            ds = self._pad_to_divisible(ds)
            net.last_batch_size = ds.num_examples()
            return ds

        # PW always runs under a plan: padding needs before_batch anyway,
        # and last_batch_index must track even checkpointer-less fits
        # (ElasticTrainer reads it after a stop)
        plan = RecoveryPlan(
            net, checkpointer=checkpointer, checkpoint_every=checkpoint_every,
            resume=resume, stop_fn=stop_fn, preemption=preemption,
            prepare=prepare,
            restore_fn=(lambda: checkpointer.restore_into_wrapper(self))
            if checkpointer is not None else None)

        if isinstance(data, MultiDataSet):
            iterable: Any = [data]
        else:
            iterable = as_iterator(data, labels, batch_size)
            if self.prefetch:
                iterable = iterable.async_(self.prefetch)
        if device_prefetch and self._nproc == 1:
            # Pad on host, then land every leaf pre-sharded across the
            # mesh one batch ahead of compute — the spine's batch
            # placement in ONE device_put per leaf.
            iterable = DevicePrefetchIterator(
                iterable, depth=max(2, int(steps_per_dispatch)),
                put_fn=self.spine.put_batch,
                transform=self._pad_to_divisible)

        def epoch_start():
            plan.epoch_start()
            self.last_batch_index = plan.last_batch_index

        def after_dispatch(bi):
            plan.after_dispatch(bi)
            self.last_batch_index = plan.last_batch_index

        net._loss_tracker.sync_every = int(sync_every)
        from deeplearning4j_tpu.observe import get_flight, get_registry

        reg = get_registry()
        reg.gauge("train_replicas").set(self.mesh.devices.size)
        reg.gauge("train_steps_per_dispatch").set(steps_per_dispatch)
        # multi-replica fits are where HBM headroom actually bites
        # (replicated params + updater state per device): breadcrumb the
        # topology so a flight dump names the mesh it died on
        get_flight().record("parallel_fit", replicas=int(self.mesh.devices.size),
                            steps_per_dispatch=int(steps_per_dispatch),
                            processes=int(self._nproc),
                            mesh_axes={str(a): int(self.mesh.shape[a])
                                       for a in self.mesh.axis_names},
                            opt_state_sharded=bool(
                                self.spine.shard_opt_state))
        execu = TrainingExecutor(
            net, step=self._step, fused_step=self._fused_step,
            can_fuse=self._can_fuse, steps_per_dispatch=steps_per_dispatch,
            before_batch=plan.before_batch, after_dispatch=after_dispatch,
            epoch_start=epoch_start, epoch_end=plan.epoch_end,
            mesh_ctx=self.spine)
        run_with_recovery(execu, plan, iterable, epochs)
        self.last_batch_index = plan.last_batch_index
        self.stopped_early = execu.stopped  # authoritative for ElasticTrainer
        return net

    def _put_batch(self, x):
        """Multi-controller feed: lift this process's local slice into the
        global batch array (concatenation over processes)."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self._put_batch(v) for k, v in x.items()}
        return put_global_batch(x, self._batch_sharding_like(x))

    def _step(self, ds):
        net = self.net
        net._rng, k = jax.random.split(net._rng)
        if self._nproc > 1:
            step = put_global(np.int32(net.iteration), self._rep)
            k = put_global(k, self._rep)
        else:
            step = jnp.asarray(net.iteration, jnp.int32)
        if self._graph:
            feats, labs, fms, lms = net._to_dicts(ds, host=self._nproc > 1)
            if self._nproc > 1:
                feats, labs, fms, lms = (self._put_batch(feats),
                                         self._put_batch(labs),
                                         self._put_batch(fms),
                                         self._put_batch(lms))
            args = (net.params_tree, net.updater_state, net.state_tree, step,
                    feats, labs, fms, lms, k)
            key = ("g", tuple(sorted(feats)), tuple(sorted(labs)),
                   fms is not None, lms is not None)
            fn = self._get_step(key, args)
            (net.params_tree, net.updater_state, net.state_tree, loss
             ) = fn(*args)
        else:
            # Multi-controller: keep the local slice on host (numpy) so
            # put_global_batch uploads once — no device round-trip.
            conv = (lambda a, dt=None: np.asarray(a, dt)) if self._nproc > 1 \
                else jnp.asarray
            feats = conv(ds.features, net.dtype)
            labs = None if ds.labels is None else conv(ds.labels)
            fm = (None if ds.features_mask is None
                  else conv(ds.features_mask))
            lm = (None if ds.labels_mask is None
                  else conv(ds.labels_mask))
            if self._nproc > 1:
                feats, labs, fm, lm = (self._put_batch(feats),
                                       self._put_batch(labs),
                                       self._put_batch(fm),
                                       self._put_batch(lm))
            args = (net.params_tree, net.updater_state, net.state_tree, step,
                    feats, labs, fm, lm, k, None)
            key = ("m", ds.features.ndim,
                   0 if ds.labels is None else ds.labels.ndim,
                   ds.features_mask is not None, ds.labels_mask is not None)
            fn = self._get_step(key, args)
            (net.params_tree, net.updater_state, net.state_tree, loss, _
             ) = fn(*args)
        # Deferred sync: replicated device scalar; LossTracker materializes.
        return loss

    # --------------------------------------------------- fused dispatch
    def _can_fuse(self, ds) -> bool:
        """Multi-controller feeding goes through put_global_batch with
        per-step host staging — fusion is single-controller only."""
        return self._nproc == 1

    def _stacked_sharding_like(self, x):
        """(K, batch, ...) stack: scan axis replicated, batch sharded."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self._stacked_sharding_like(v) for k, v in x.items()}
        return NamedSharding(
            self.mesh, P(None, self.batch_axis, *([None] * (x.ndim - 2))))

    def _put_stacked(self, x):
        """Place a (K, batch, ...) stack with the scan axis replicated and
        the batch axis sharded across the mesh."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self._put_stacked(v) for k, v in x.items()}
        return jax.device_put(x, self._stacked_sharding_like(x))

    def _get_fused_step(self, key, example_args):
        if key in self._jit_cache:
            return self._jit_cache[key]
        k = key[1]
        base = self.net.make_step_fn()
        # rng rides in the scan carry and splits in-graph — the identical
        # sequential `net._rng, r = split(net._rng)` chain as the unfused
        # step, with no per-step host dispatch.
        if self._graph:
            def fused(params, opt_state, states, step0, rng, feats, labs,
                      fms, lms):
                def body(carry, xs):
                    p, o, s, step, r = carry
                    f, l, fm, lm = xs
                    r, sub = jax.random.split(r)
                    new_p, new_o, persist, loss = base(
                        p, o, s, step, f, l, fm, lm, sub)
                    return (new_p, new_o, persist, step + 1, r), loss

                (params, opt_state, states, _, rng), losses = jax.lax.scan(
                    body, (params, opt_state, states, step0, rng),
                    (feats, labs, fms, lms))
                return params, opt_state, states, rng, losses
        else:
            def fused(params, opt_state, states, step0, rng, feats, labs,
                      fms, lms):
                def body(carry, xs):
                    p, o, s, step, r = carry
                    f, l, fm, lm = xs
                    r, sub = jax.random.split(r)
                    new_p, new_o, persist, loss, _ = base(
                        p, o, s, step, f, l, fm, lm, sub, None)
                    return (new_p, new_o, persist, step + 1, r), loss

                (params, opt_state, states, _, rng), losses = jax.lax.scan(
                    body, (params, opt_state, states, step0, rng),
                    (feats, labs, fms, lms))
                return params, opt_state, states, rng, losses

        # Both ends of the K-step scan are pinned: the partitioner must
        # carry the replica-sharded moments through the whole window and
        # hand them back in place — without the explicit in_shardings it
        # re-replicates the carry and the donated moment buffers become
        # unusable (a reshard + 2x moment HBM per dispatch window).
        # (params, opt, states, step0, rng, feats, labs, fms, lms)
        _, _, _, _, _, feats, labs, fms, lms = example_args
        in_sh = (self._params_sh, self._opt_sh, self._rep, self._rep,
                 self._rep,
                 self._stacked_sharding_like(feats),
                 self._stacked_sharding_like(labs),
                 self._stacked_sharding_like(fms),
                 self._stacked_sharding_like(lms))
        # (params, opt, states, rng, losses)
        out_sh = (self._params_sh, self._opt_sh, self._rep, self._rep,
                  self._rep)
        fn = donatemon.instrument(
            jax.jit(fused, in_shardings=in_sh, out_shardings=out_sh,
                    donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="ParallelWrapper._fused_step",
            arg_names=("params", "opt_state", "states"))
        self._jit_cache[key] = fn
        # read back through the cache (probe wrapping), as in _get_step
        return self._jit_cache[key]

    def _fused_step(self, batches):
        """K pre-sharded batches → one sharded `lax.scan` dispatch."""
        net = self.net
        first = batches[0]
        step0 = np.int32(net.iteration)
        if self._graph:
            f0 = first.features
            host = isinstance(
                f0[0] if hasattr(first, "features_masks") else f0,
                np.ndarray)
            conv = [net._to_dicts(b, host=host) for b in batches]
            stack = (np.stack if host else jnp.stack)

            def stk(idx):
                head = conv[0][idx]
                if head is None:
                    return None
                # host batches stack as numpy, so _put_stacked's
                # device_put is the single host→device hop per tensor
                return self._put_stacked(
                    {n: stack([c[idx][n] for c in conv]) for n in head})

            key = ("gf", len(batches), tuple(sorted(conv[0][0])),
                   tuple(sorted(conv[0][1])),
                   conv[0][2] is not None, conv[0][3] is not None)
            args = (net.params_tree, net.updater_state, net.state_tree,
                    step0, net._rng, stk(0), stk(1), stk(2), stk(3))
            fn = self._get_fused_step(key, args)
            (net.params_tree, net.updater_state, net.state_tree, net._rng,
             losses) = fn(*args)
        else:
            def stk(get, dt=None):
                vals = [get(b) for b in batches]
                if vals[0] is None:
                    return None
                if all(isinstance(v, np.ndarray) for v in vals):
                    arr = np.stack(vals)
                    if dt is not None:
                        arr = arr.astype(dt, copy=False)
                else:
                    arr = jnp.stack([jnp.asarray(v, dt) for v in vals])
                return self._put_stacked(arr)

            key = ("mf", len(batches), first.features.ndim,
                   0 if first.labels is None else first.labels.ndim,
                   first.features_mask is not None,
                   first.labels_mask is not None)
            args = (net.params_tree, net.updater_state, net.state_tree,
                    step0, net._rng,
                    stk(lambda b: b.features, net.dtype),
                    stk(lambda b: b.labels),
                    stk(lambda b: b.features_mask),
                    stk(lambda b: b.labels_mask))
            fn = self._get_fused_step(key, args)
            (net.params_tree, net.updater_state, net.state_tree, net._rng,
             losses) = fn(*args)
        return losses
