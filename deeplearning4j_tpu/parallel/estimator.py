"""Estimator/Model pipeline wrappers — dl4j-spark-ml parity, sklearn-shaped.

Reference parity: `dl4j-spark-ml/.../SparkDl4jNetwork.scala` wraps a network
config + TrainingMaster as a Spark ML `Estimator` whose `fit` returns a
`Model` usable in ML pipelines (SURVEY §2.4). The idiomatic Python analogue
is the scikit-learn estimator protocol (fit/predict/get_params), which
composes with sklearn Pipelines the way the Scala class composed with Spark
ML pipelines.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from deeplearning4j_tpu.parallel.training_master import (
    DistributedTrainingMaster, TrainingMaster,
)


class NetworkEstimator:
    """Fit a network config into a trained model, optionally through a
    TrainingMaster (distributed) — `new SparkDl4jNetwork(conf, tm).fit(df)`
    becomes `NetworkEstimator(conf, training_master=tm).fit(X, y)`."""

    def __init__(self, conf, *, training_master: Optional[TrainingMaster]
                 = None, epochs: int = 1, batch_size: int = 32):
        self.conf = conf
        self.training_master = training_master
        self.epochs = epochs
        self.batch_size = batch_size
        self.model_: Optional[Any] = None

    # sklearn protocol ------------------------------------------------
    def get_params(self, deep: bool = True):
        return {"conf": self.conf, "training_master": self.training_master,
                "epochs": self.epochs, "batch_size": self.batch_size}

    def set_params(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)
        return self

    def _build(self):
        from deeplearning4j_tpu.models import (
            ComputationGraph, MultiLayerNetwork,
        )

        if hasattr(self.conf, "vertices"):
            return ComputationGraph(self.conf).init()
        return MultiLayerNetwork(self.conf).init()

    def fit(self, X, y=None):
        net = self._build()
        if self.training_master is not None:
            self.training_master.execute_training(
                net, X, y, batch_size=self.batch_size, epochs=self.epochs)
        else:
            net.fit(X, y, epochs=self.epochs, batch_size=self.batch_size)
        self.model_ = net
        return self

    def predict(self, X):
        if self.model_ is None:
            raise RuntimeError("fit() before predict()")
        out = self.model_.output(X)
        if isinstance(out, dict):
            out = next(iter(out.values()))
        return np.argmax(np.asarray(out), axis=-1)

    def predict_proba(self, X):
        if self.model_ is None:
            raise RuntimeError("fit() before predict()")
        out = self.model_.output(X)
        if isinstance(out, dict):
            out = next(iter(out.values()))
        return np.asarray(out)

    def score(self, X, y):
        pred = self.predict(X)
        true = np.argmax(np.asarray(y), axis=-1) if np.asarray(y).ndim > 1 \
            else np.asarray(y)
        return float(np.mean(pred == true))
