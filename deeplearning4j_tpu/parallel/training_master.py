"""Multi-node training masters — the Spark layer-5 outer driver, TPU-native.

Reference parity: `spark/dl4j-spark/.../api/TrainingMaster.java:76-158`
(the SPI) and `impl/paramavg/ParameterAveragingTrainingMaster.java` — the
reference splits the RDD into `numWorkers·batchSize·averagingFrequency`-
example splits (`:346-357`), runs `ExecuteWorkerFlatMap` minibatch loops on
executors, then `treeAggregate`s (params, updaterState, score) with
configurable depth (`:860-867`), divides, and re-broadcasts (SURVEY §3.4).

TPU-native redesign:
- Inside one host/pod slice, "workers" are NOT processes exchanging
  serialized parameters: `DistributedTrainingMaster` drives the model's own
  sharded-jit step over the global device mesh (ICI allreduce — exact
  per-step averaging), with each controller process feeding its
  `host_local_shard` of every split (the multi-controller SPMD analogue of
  the driver→executor broadcast).
- `ParameterAveragingTrainingMaster` preserves the reference's *algorithm*
  (local SGD / periodic averaging — useful over DCN where per-step
  allreduce is too chatty, and for parity testing): N logical workers each
  run `averaging_frequency` minibatches from their partition of the split,
  then params + updater state are combined by a depth-limited pairwise
  reduction tree (treeAggregate equivalent) and re-broadcast.
- Phase timing stats mirror ParameterAveragingTrainingMasterStats
  (`collect_training_stats(true)` → split/fit/aggregate wall times).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.iterators import as_iterator

_tmap = jax.tree_util.tree_map


@dataclasses.dataclass
class PhaseStats:
    """One split's phase timings (reference: EventStats / StatsUtils).
    `start_ms` is a wall-clock stamp from the configured TimeSource
    (SystemClock or NTP — `utils/timesource.py`), so timelines from
    multiple hosts can line up like the reference's NTP-corrected
    EventStats."""

    split_index: int
    n_examples: int
    fit_ms: float
    aggregate_ms: float
    broadcast_ms: float
    score: float
    start_ms: float = 0.0


class TrainingMaster:
    """SPI: how to distribute `fit` over a cluster.

    Reference: `api/TrainingMaster.java:76-158` (executeTraining /
    getWorkerInstance / processResults collapsed into one method — the
    serialization-driven split of the Spark SPI has no TPU purpose)."""

    def execute_training(self, net, data, labels=None, *,
                         batch_size: int = 32, epochs: int = 1) -> None:
        raise NotImplementedError

    def training_stats(self) -> List[PhaseStats]:
        return []


def _allgather_host(value):
    """Gather a HOST-side value (or pytree) from every process in ONE
    collective; each leaf gains a leading process-index axis. The DCN
    hop of parameter averaging — processes hold different values after
    training their own shards (contrast distributed.put_global, which
    assumes identical values)."""
    from jax.experimental import multihost_utils

    return jax.tree_util.tree_map(
        np.asarray, multihost_utils.process_allgather(value))


def _tree_reduce_pairwise(trees: List[Any], depth: int):
    """Sum pytrees with a bounded-depth reduction tree — the moral
    equivalent of RDD.treeAggregate(depth) (`:860-867`): pairwise rounds
    bound peak temporary memory the way executor-side combining bounds
    driver load."""
    trees = list(trees)
    rounds = 0
    while len(trees) > 1 and rounds < depth:
        nxt = []
        for i in range(0, len(trees) - 1, 2):
            nxt.append(_tmap(lambda a, b: a + b, trees[i], trees[i + 1]))
        if len(trees) % 2:
            nxt.append(trees[-1])
        trees = nxt
        rounds += 1
    # Fold whatever remains linearly (depth exhausted).
    acc = trees[0]
    for t in trees[1:]:
        acc = _tmap(lambda a, b: a + b, acc, t)
    return acc


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Local-SGD periodic parameter averaging.

    Mirrors `ParameterAveragingTrainingMaster.java`: the dataset is cut
    into splits of `num_workers * batch_size * averaging_frequency`
    examples (`:346-357`); each worker runs `averaging_frequency`
    minibatches from its partition starting from the current global params;
    params AND updater state are averaged (`processResults:860-900`) and
    re-broadcast for the next split. Workers share one jitted step (same
    XLA program; distinct param trees) — the TPU analogue of executor-side
    `network.fit` per minibatch."""

    def __init__(self, *, num_workers: int = 2, batch_size: int = 32,
                 averaging_frequency: int = 5, aggregation_depth: int = 2,
                 average_updater_state: bool = True,
                 collect_training_stats: bool = False):
        if num_workers < 1 or averaging_frequency < 1:
            raise ValueError("num_workers and averaging_frequency must be >=1")
        self.num_workers = num_workers
        self.batch_size = batch_size
        self.averaging_frequency = averaging_frequency
        self.aggregation_depth = max(1, aggregation_depth)
        self.average_updater_state = average_updater_state
        self.collect_stats = collect_training_stats
        self._stats: List[PhaseStats] = []

    # -- split generation (reference getSplits via SparkUtils.repartition)
    def _splits(self, it):
        per_split = (self.num_workers * self.batch_size
                     * self.averaging_frequency)
        buf_x, buf_y, n = [], [], 0
        for ds in it:
            buf_x.append(np.asarray(ds.features))
            buf_y.append(np.asarray(ds.labels))
            n += buf_x[-1].shape[0]
            if n >= per_split:
                yield np.concatenate(buf_x), np.concatenate(buf_y)
                buf_x, buf_y, n = [], [], 0
        if n:
            yield np.concatenate(buf_x), np.concatenate(buf_y)

    def execute_training(self, net, data, labels=None, *,
                         batch_size: Optional[int] = None,
                         epochs: int = 1, start_split: int = 0,
                         on_split_end=None) -> None:
        """Multi-controller (jax.process_count() > 1): each process runs
        its `num_workers` LOCAL workers over its `host_local_shard` of the
        data, then params/updater state are averaged ACROSS processes too
        — local SGD over DCN, the Spark driver↔executor flow
        (`ParameterAveragingTrainingMaster.java` processResults) where
        per-step allreduce is too chatty. Global worker count =
        num_workers * process_count."""
        from deeplearning4j_tpu.parallel.distributed import (
            host_local_shard, process_count,
        )

        if process_count() > 1:
            if labels is None:
                raise NotImplementedError(
                    "multi-controller execute_training requires (features, "
                    "labels) arrays so each process can take its "
                    "host_local_shard")
            # balanced: the n % nproc tail is round-robined across
            # processes instead of silently dropped (advisor r3 finding).
            # Shards may then differ by one example; pad the short ones
            # (wrap-around) up to the max shard size so every process runs
            # the SAME number of splits — the per-split allgather below
            # deadlocks if split counts drift.
            n_all = len(data)
            if n_all < process_count():
                # deterministic on every process (same n_all), so all
                # raise together instead of the empty-shard processes
                # crashing while the rest deadlock in the allgather
                raise ValueError(
                    f"dataset of {n_all} examples cannot shard over "
                    f"{process_count()} processes")
            sl = host_local_shard(n_all, balanced=True)
            data, labels = data[sl], labels[sl]
            target = -(-n_all // process_count())  # ceil = max shard size
            if len(data) < target:
                import numpy as _np

                fill = _np.arange(target - len(data)) % len(data)
                data = _np.concatenate([_np.asarray(data),
                                        _np.asarray(data)[fill]])
                labels = _np.concatenate([_np.asarray(labels),
                                          _np.asarray(labels)[fill]])
        bs = batch_size or self.batch_size
        step = jax.jit(net.make_step_fn())
        graph = hasattr(net, "conf") and hasattr(net.conf, "vertices")
        # `si` counts splits GLOBALLY across epochs so preemption
        # recovery can skip already-trained splits (`start_split`) after
        # a checkpoint restore — the restored net already carries their
        # effect (params + iteration), so skipped splits touch nothing.
        # `on_split_end(si, net)` is the per-split hook (the reference's
        # TrainingHook / ParameterAveragingTrainingHook seam,
        # `spark/parameterserver/ParameterServerTrainingHook.java:22`).
        si = 0
        for _ in range(epochs):
            it = as_iterator(data, labels, bs)
            for xs, ys in self._splits(it):
                if si >= start_split:
                    self._run_split(net, step, si, xs, ys, bs, graph)
                    if on_split_end is not None:
                        on_split_end(si, net)
                si += 1
        net.score_ = self._stats[-1].score if self._stats else net.score_

    def _run_split(self, net, step, si, xs, ys, bs, graph):
        start_ms = 0.0
        if self.collect_stats:  # keep TimeSource (possibly NTP) off the
            from deeplearning4j_tpu.utils.timesource import (  # hot path
                TimeSourceProvider,
            )

            start_ms = TimeSourceProvider.get_instance().current_time_millis()
        t0 = time.perf_counter()
        parts = np.array_split(np.arange(xs.shape[0]), self.num_workers)
        in_name = (net.conf.network_inputs[0]
                   if graph and getattr(net.conf, "network_inputs", None)
                   else "input")
        out_name = (net.conf.network_outputs[0]
                    if graph and getattr(net.conf, "network_outputs", None)
                    else "output")
        results = []
        scores = []
        for w, idx in enumerate(parts):
            if idx.size == 0:
                continue
            params = net.params_tree
            opt = net.updater_state
            states = net.state_tree
            itn = jnp.asarray(net.iteration, jnp.int32)
            # fold in the GLOBAL worker index so multi-controller pods
            # give every logical worker a distinct stream (and match the
            # equivalent single-process num_workers*nproc run exactly)
            gw = jax.process_index() * self.num_workers + w
            wrng = jax.random.fold_in(jax.random.PRNGKey(net.iteration), gw)
            loss = None
            for k in range(self.averaging_frequency):
                rng = jax.random.fold_in(wrng, k)  # fresh dropout per step
                lo = (k * bs) % idx.size
                # Wrap to a FIXED bs so the jitted step sees one static batch
                # shape (a short trailing chunk would trigger a recompile).
                sel = idx[(lo + np.arange(bs)) % idx.size]
                fx, fy = jnp.asarray(xs[sel]), jnp.asarray(ys[sel])
                if graph:
                    out = step(params, opt, states, itn,
                               {in_name: fx}, {out_name: fy},
                               None, None, rng)
                else:
                    out = step(params, opt, states, itn, fx, fy,
                               None, None, rng, None)
                params, opt, states, loss = out[0], out[1], out[2], out[3]
                itn = itn + 1
            if loss is not None:
                scores.append(float(loss))
                results.append((params, opt))
        score = float(np.mean(scores)) if scores else float("nan")
        t1 = time.perf_counter()
        n = len(results)
        avg_params = _tmap(lambda s: s / n, _tree_reduce_pairwise(
            [r[0] for r in results], self.aggregation_depth))
        if self.average_updater_state:
            avg_opt = _tmap(lambda s: s / n, _tree_reduce_pairwise(
                [r[1] for r in results], self.aggregation_depth))
        else:
            avg_opt = net.updater_state
        if jax.process_count() > 1:
            # second aggregation level: across controller processes
            # (the treeAggregate->driver hop; every process ends the
            # split with IDENTICAL averaged state). ONE gather carries
            # params + opt state + score — a single DCN collective per
            # split, not one per pytree leaf.
            bundle = {"p": avg_params, "s": np.float64(score)}
            if self.average_updater_state:
                bundle["o"] = avg_opt
            gathered = _allgather_host(bundle)
            mean = jax.tree_util.tree_map(lambda g: g.mean(axis=0),
                                          gathered)
            avg_params = mean["p"]
            score = float(mean["s"])
            if self.average_updater_state:
                avg_opt = mean["o"]
        t2 = time.perf_counter()
        # "Broadcast": install averaged state as the next split's start —
        # dtype-preserving, like `params.divi(aggCount)` + setParameters.
        net.params_tree = _tmap(
            lambda a, b: a.astype(b.dtype), avg_params, net.params_tree)
        net.updater_state = _tmap(
            lambda a, b: a.astype(b.dtype), avg_opt, net.updater_state)
        net.iteration += self.averaging_frequency
        t3 = time.perf_counter()
        if self.collect_stats:
            self._stats.append(PhaseStats(
                split_index=si, n_examples=int(xs.shape[0]),
                fit_ms=(t1 - t0) * 1e3, aggregate_ms=(t2 - t1) * 1e3,
                broadcast_ms=(t3 - t2) * 1e3, score=score,
                start_ms=start_ms))
        else:
            self._stats.append(PhaseStats(si, int(xs.shape[0]), 0, 0, 0,
                                          score))

    def training_stats(self) -> List[PhaseStats]:
        return self._stats


class DistributedTrainingMaster(TrainingMaster):
    """Per-step exact averaging over the global device mesh.

    The TPU-native layer 5: where the reference shipped parameters through
    Spark every `averagingFrequency` iterations, a pod slice allreduces
    gradients over ICI every step inside one XLA program. In multi-
    controller mode (jax.distributed initialized), each process feeds its
    host-local shard of the batch; single-process, this degrades gracefully
    to ParallelWrapper over the local mesh."""

    def __init__(self, *, mesh=None, collect_training_stats: bool = False):
        self.mesh = mesh
        self.collect_stats = collect_training_stats
        self._stats: List[PhaseStats] = []

    def execute_training(self, net, data, labels=None, *,
                         batch_size: int = 32, epochs: int = 1) -> None:
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
        from deeplearning4j_tpu.parallel.distributed import (
            host_local_shard, process_count,
        )

        nproc = process_count()
        if nproc > 1:
            if labels is None:
                # Iterators/DataSets carry no global index to shard by;
                # feeding them unsharded would silently duplicate every
                # example on every process — refuse instead.
                raise NotImplementedError(
                    "multi-controller execute_training requires (features, "
                    "labels) arrays so each process can take its "
                    "host_local_shard; pre-shard iterator inputs manually")
            if len(data) < nproc:
                # deterministic on every process: all raise together
                raise ValueError(
                    f"dataset of {len(data)} examples cannot shard over "
                    f"{nproc} processes")
            sl = host_local_shard(len(data))
            dropped = len(data) % nproc
            if dropped:
                import warnings

                warnings.warn(
                    f"DistributedTrainingMaster: {dropped} of {len(data)} "
                    "examples dropped (dataset does not divide over "
                    f"{nproc} processes; SPMD batch assembly needs equal "
                    "per-host counts — pad the dataset to keep them)")
            data, labels = data[sl], labels[sl]
            # batch_size is the GLOBAL batch: each process iterates its
            # shard in host-local slices; ParallelWrapper._put_batch
            # reassembles the global array (concatenation over processes).
            if batch_size % nproc:
                raise ValueError(
                    f"global batch_size {batch_size} must divide over "
                    f"{nproc} processes")
            batch_size //= nproc
        start_ms = 0.0
        if self.collect_stats:
            from deeplearning4j_tpu.utils.timesource import (
                TimeSourceProvider,
            )

            start_ms = TimeSourceProvider.get_instance().current_time_millis()
        t0 = time.perf_counter()
        pw = ParallelWrapper(net, mesh=self.mesh)
        pw.fit(data, labels, epochs=epochs, batch_size=batch_size)
        if self.collect_stats:
            self._stats.append(PhaseStats(
                0, len(data) if hasattr(data, "__len__") else -1,
                (time.perf_counter() - t0) * 1e3, 0.0, 0.0,
                float(net.score_), start_ms=start_ms))

    def training_stats(self) -> List[PhaseStats]:
        return self._stats


def distributed_evaluate(net, features, labels, *, batch_size: int = 32):
    """Distributed classification evaluation: each controller process
    evaluates its `host_local_shard`, confusion matrices sum across
    processes in one gather. The Spark evaluation seam
    (`SparkDl4jMultiLayer.evaluate(JavaRDD)` -> executor-side eval +
    treeAggregate merge of Evaluation objects), multi-controller style.
    Single-process it degrades to a plain `net.evaluate`."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.eval import Evaluation
    from deeplearning4j_tpu.parallel.distributed import (
        process_count, process_index,
    )

    nproc = process_count()
    n = len(features)
    n_classes = int(np.asarray(labels).shape[-1])
    if nproc > 1:
        # Unlike training shards, eval shards need not be equal-sized
        # (the only collective is the fixed-shape confusion gather), so
        # the LAST process takes the remainder — no example dropped.
        per, k = n // nproc, process_index()
        sl = slice(k * per, (k + 1) * per if k < nproc - 1 else n)
    else:
        sl = slice(None)
    ev = net.evaluate(ArrayDataSetIterator(
        features[sl], labels[sl], batch_size, shuffle=False))
    ev._ensure(n_classes)          # empty shard: zero matrix, not None
    if nproc > 1:
        mats = _allgather_host(np.asarray(ev.confusion.matrix))  # [P,C,C]
        # process_allgather adds NO leading axis when the runtime has a
        # single process (identity gather) — normalize before the merge
        # sum or axis 0 would eat a confusion-matrix dimension.
        mats = np.asarray(mats).reshape(
            (-1,) + ev.confusion.matrix.shape)
        merged = Evaluation(num_classes=ev.num_classes,
                            labels=ev.label_names)
        merged._ensure(ev.num_classes)
        merged.confusion.matrix = mats.sum(axis=0, dtype=np.int64)
        return merged
    return ev


def export_timeline_html(stats: List[PhaseStats], path: str, *,
                         title: str = "Training phase timeline") -> str:
    """Render collected PhaseStats as an HTML timeline + summary table.

    Reference: `spark/stats/StatsUtils.java` exportStatsAsHtml — the
    fit/aggregate/broadcast phases of every split on lanes over wall
    time. Built from the reusable UI components (ui/components.py), so
    the chart payload is also available as JSON via .to_dict()."""
    from deeplearning4j_tpu.ui.components import (
        ChartTimeline, ComponentDiv, ComponentTable, Style,
    )

    lanes = ("fit", "aggregate", "broadcast")
    entries = []
    t = 0.0
    base = min((s.start_ms for s in stats if s.start_ms), default=0.0)
    for s in stats:
        t0 = (s.start_ms - base) if s.start_ms else t
        spans = ((0, s.fit_ms), (1, s.aggregate_ms), (2, s.broadcast_ms))
        cur = t0
        for lane, dur in spans:
            if dur > 0:
                entries.append((lane, cur, cur + dur,
                                f"split {s.split_index}: "
                                f"{lanes[lane]} {dur:.1f} ms"))
                cur += dur
        t = max(t, cur)
    chart = ChartTimeline(
        title=title, lanes=lanes, entries=tuple(entries),
        style=Style(width=960, height=220))
    table = ComponentTable(
        title="Per-split phase timings",
        header=("split", "examples", "fit ms", "aggregate ms",
                "broadcast ms", "score"),
        rows=tuple((str(s.split_index), str(s.n_examples),
                    f"{s.fit_ms:.1f}", f"{s.aggregate_ms:.1f}",
                    f"{s.broadcast_ms:.1f}", f"{s.score:.5f}")
                   for s in stats))
    from html import escape

    doc = ComponentDiv(children=(chart, table))
    html = ("<!doctype html><html><head><title>" + escape(title)
            + "</title>"
            "<style>table.uic{border-collapse:collapse;font-size:13px}"
            "table.uic td,table.uic th{border:1px solid #ddd;"
            "padding:3px 8px}</style></head><body>"
            + doc.render() + "</body></html>")
    with open(path, "w") as f:
        f.write(html)
    return html
