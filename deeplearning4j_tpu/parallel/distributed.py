"""Multi-host (multi-process) initialization — the DCN-side coordination.

Reference parity: the reference's multi-node transports (Spark driver +
executors, Aeron UDP parameter server — SURVEY §5 'distributed communication
backend') are replaced by JAX's multi-controller runtime: every host runs the
same program, `jax.distributed.initialize` wires the PJRT coordination
service over DCN, and `jax.devices()` becomes the GLOBAL device list so the
same mesh/pjit code scales from 1 chip to a multi-pod slice unchanged.

The Spark TrainingMaster SPI's role (split orchestration, fault tolerance)
maps to: outer job scheduler (GKE/Borg-style) + deterministic data sharding
by process index (`host_local_shard`) + checkpoint/resume
(models/serialize.CheckpointManager) for preemption recovery.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize the multi-host runtime. No-ops on single-process runs.

    Args default from the standard env vars (JAX_COORDINATOR_ADDRESS etc. /
    TPU metadata on Cloud TPU, where initialize() autodetects everything).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        return  # single process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def host_local_shard(n_examples: int, balanced: bool = False) -> slice:
    """Deterministic per-host data shard [start, stop) — the input-pipeline
    contract for multi-host data parallelism (each host feeds only its local
    devices' portion of the global batch).

    With ``balanced=False`` (default) the ``n_examples % process_count``
    tail is DROPPED — every process gets the same count (what SPMD batch
    assembly requires). ``balanced=True`` round-robins the remainder to
    the first processes instead, so the union of shards covers every
    example (local-SGD / evaluation flows where counts may differ)."""
    nproc = jax.process_count()
    per, rem = divmod(n_examples, nproc)
    pi = jax.process_index()
    if not balanced:
        start = pi * per
        return slice(start, start + per)
    start = pi * per + min(pi, rem)
    return slice(start, start + per + (1 if pi < rem else 0))


def sync_global_devices(tag: str = "barrier") -> None:
    """Cross-host barrier (psum of 1 over all devices)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def put_global(x, sharding):
    """device_put that works in multi-controller mode.

    Single-process this is `jax.device_put`. Multi-process, each host is
    assumed to hold the SAME full value `x` (replicated params, scalars,
    rng keys), and each process supplies only its addressable shards —
    the multi-controller analogue of the reference's driver->executor
    parameter broadcast (`ParameterAveragingTrainingMaster.java`
    processResults re-broadcast)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    try:
        typed_key = jax.numpy.issubdtype(x.dtype, jax.dtypes.prng_key)
    except Exception:
        typed_key = False
    if typed_key:  # typed PRNG keys: round-trip through raw key data
        # graft: allow-sync(global key assembly requires host key data)
        data = np.asarray(jax.random.key_data(x))
        _check_replicated_consistency(data)
        raw = jax.make_array_from_callback(
            data.shape, sharding, lambda idx: data[idx])
        return jax.random.wrap_key_data(raw)
    x = np.asarray(x)
    _check_replicated_consistency(x)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: x[idx])


def _check_replicated_consistency(x) -> None:
    """Debug guard (DL4J_TPU_CHECK_REPLICATED=1): allgather a checksum of
    the supposedly process-replicated value and fail fast if hosts
    diverge (differently seeded nets, drifted RNG streams) instead of
    silently assembling a global array that mixes values from different
    hosts. Off by default — it costs one DCN collective per call."""
    import os

    if os.environ.get("DL4J_TPU_CHECK_REPLICATED") != "1":
        return
    import zlib

    from jax.experimental import multihost_utils

    digest = np.uint32(zlib.adler32(np.ascontiguousarray(x).tobytes()))
    all_digests = np.asarray(multihost_utils.process_allgather(digest))
    if not (all_digests == all_digests[0]).all():
        raise AssertionError(
            "put_global: replicated value differs across processes "
            f"(per-process adler32 = {all_digests.tolist()}); every host "
            "must hold an identical copy")


def put_global_batch(local, sharding):
    """Assemble a GLOBAL batch from per-process local arrays.

    Each process passes its `host_local_shard` slice; the global array is
    their concatenation in process order along the sharded (batch) axis.
    This is the input-feeding contract of multi-controller SPMD: no host
    ever materializes the global batch (the reference instead ships
    serialized DataSets through Spark; SURVEY §3.4)."""
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local))
