"""Ring attention — sequence/context parallelism over a mesh axis.

No reference counterpart (SURVEY §5: 'No ring attention / context parallel…
RNN era'); this is the green-field long-context mechanism the charter
requires. Design: the sequence axis is sharded over the `seq` mesh axis;
each device holds a local block of Q/K/V. K/V blocks rotate around the ring
via `lax.ppermute` while each device accumulates its queries' attention with
the numerically-stable online-softmax (flash-attention style) running
(max, sum, out) triple — so peak memory is O(T_local²) instead of O(T²) and
the K/V transfer rides ICI neighbor links (the ring pattern maps exactly
onto the TPU torus).

Blockwise comm/compute overlap: each ppermute is issued before the block
accumulation it hides behind (XLA schedules the collective-permute
asynchronously).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import AXIS_SEQ


# ---------------------------------------------------- layer integration
@dataclasses.dataclass(frozen=True)
class _SeqParallelCtx:
    mesh: Mesh
    axis: str


_SEQ_CTX: contextvars.ContextVar[Optional[_SeqParallelCtx]] = \
    contextvars.ContextVar("sequence_parallel_ctx", default=None)


@contextlib.contextmanager
def sequence_parallel(mesh: Mesh, axis: str = AXIS_SEQ):
    """Route every MultiHeadAttention (and thus TransformerEncoderBlock)
    applied inside this context through ring attention over `axis` —
    sequence parallelism at the model level, no layer changes:

        with sequence_parallel(make_mesh({"seq": 8})):
            net.fit(x, y, ...)

    The swap happens at TRACE time: wrap the calls that trace/compile
    (fit/output); a step compiled inside the context stays
    sequence-parallel when reused."""
    token = _SEQ_CTX.set(_SeqParallelCtx(mesh, axis))
    try:
        yield
    finally:
        _SEQ_CTX.reset(token)


def current_sequence_mesh() -> Optional[_SeqParallelCtx]:
    return _SEQ_CTX.get()


class SeqCtxJitCache:
    """Mixin: a `_jit_cache` dict partitioned by the active
    sequence-parallel context. Any object caching compiled traces of a
    forward that consults `current_sequence_mesh()` at trace time must
    never reuse a trace across context boundaries — a ring trace outside
    the context (or a dense trace inside it) is silently wrong."""

    @property
    def _jit_cache(self):
        caches = self.__dict__.setdefault("_jit_caches", {})
        cache = caches.get(current_sequence_mesh())
        if cache is None:
            # every compiled-program cache in the framework flows through
            # this property, so a counting dict here gives the
            # RecompileWatchdog full coverage of (re)compiles
            from deeplearning4j_tpu.observe.watchdog import WatchedJitCache
            cache = caches[current_sequence_mesh()] = \
                WatchedJitCache(owner=self)
        return cache


class SeqCtxSolverCache:
    """Mixin: the full-batch `_solver` cache, partitioned like
    SeqCtxJitCache (the solver holds its own compiled forward traces)."""

    @property
    def _solver(self):
        return self.__dict__.setdefault("_solvers", {}).get(
            current_sequence_mesh())

    @_solver.setter
    def _solver(self, value):
        self.__dict__.setdefault("_solvers", {})[
            current_sequence_mesh()] = value


def _block_accumulate(q, k, v, m, l, o, *, scale, q_off, k_off, causal):
    """Online-softmax accumulation of one K/V block into (m, l, o).

    q: [B,Tq,H,D]  k,v: [B,Tk,H,D]  m,l: [B,H,Tq]  o: [B,Tq,H,D]
    q_off/k_off: global offsets of the blocks (for causal masking).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qi = q_off + jnp.arange(tq)[:, None]
        ki = k_off + jnp.arange(tk)[None, :]
        s = jnp.where(ki > qi, -jnp.inf, s)
    m_blk = jnp.max(s, axis=-1)                       # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (all -inf) against NaN
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = (o * corr.transpose(0, 2, 1)[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, o_new


def attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None):
    """Single-device reference attention (used when no seq axis / tests)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tk)[None, :] > jnp.arange(tq)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _flash_shard(q, k, v, causal: bool, scale: float, interpret: bool):
    """One K/V shard through the Pallas kernel; [B,T,H,D] in/out with
    per-row lse [B,H,Tq] for cross-shard merging."""
    from deeplearning4j_tpu.ops.attention import (_fold3, _unfold3,
                                                  flash_attention_with_lse)

    B, T, H, _ = q.shape
    q3, shape = _fold3(q)
    k3, _ = _fold3(k)
    v3, _ = _fold3(v)
    o, lse = flash_attention_with_lse(q3, k3, v3, causal, scale, 512, 512,
                                      interpret)
    return _unfold3(o, shape), lse.reshape(B, H, T)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float], use_flash: bool = False,
                          interpret: bool = False):
    """Per-shard body (runs under shard_map). q/k/v: local blocks
    [B, T_local, H, D].

    Two per-shard compute paths: the XLA online-softmax accumulation
    (any backend/shape), or the Pallas flash kernel (`use_flash`) where
    each held shard is one of exactly three causal cases — fully visible
    (src < my: plain kernel), diagonal (src == my: the kernel's aligned
    causal mask), or fully masked (src > my: skipped, zero FLOPs) — and
    partial outputs merge via logaddexp of the emitted lse."""
    try:
        n = lax.axis_size(axis_name)
    # graft: allow(GL403): version probe — pre-0.5 jax has no axis_size;
    # psum of a python scalar constant-folds to the axis size statically
    except AttributeError:
        n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    scale_ = scale if scale is not None else 1.0 / (D ** 0.5)
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_flash:
        o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
        lse0 = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)

        def body(i, carry):
            k_blk, v_blk, o, lse = carry
            src = (my - i) % n
            k_nxt = lax.ppermute(k_blk, axis_name, perm)
            v_nxt = lax.ppermute(v_blk, axis_name, perm)
            if causal:
                def diag(args):
                    return _flash_shard(*args, True, scale_, interpret)

                def full(args):
                    return _flash_shard(*args, False, scale_, interpret)

                def dead(args):
                    return (jnp.zeros((B, Tq, H, D), q.dtype),
                            jnp.full((B, H, Tq), -jnp.inf, jnp.float32))

                o_i, lse_i = lax.cond(
                    src == my, diag,
                    lambda args: lax.cond(src < my, full, dead, args),
                    (q, k_blk, v_blk))
            else:
                o_i, lse_i = _flash_shard(q, k_blk, v_blk, False, scale_,
                                          interpret)
            lse_new = jnp.logaddexp(lse, lse_i)
            # exp(-inf - -inf) guard: a row with no visible keys yet
            w_old = jnp.where(jnp.isneginf(lse_new), 0.0,
                              jnp.exp(lse - lse_new))
            w_new = jnp.where(jnp.isneginf(lse_new), 0.0,
                              jnp.exp(lse_i - lse_new))
            o = (o * w_old.transpose(0, 2, 1)[..., None]
                 + o_i.astype(jnp.float32)
                 * w_new.transpose(0, 2, 1)[..., None])
            return (k_nxt, v_nxt, o, lse_new)

        _, _, o, _ = lax.fori_loop(0, n, body, (k, v, o0, lse0))
        return o.astype(q.dtype)

    m0 = jnp.full((B, H, Tq), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tq), q.dtype)
    o0 = jnp.zeros_like(q)

    def body(i, carry):
        k_blk, v_blk, m, l, o = carry
        # Block currently held arrived from device (my - i) mod n.
        src = (my - i) % n
        # Rotate early so the permute overlaps the block math below.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        m, l, o = _block_accumulate(
            q, k_blk, v_blk, m, l, o,
            scale=scale_, q_off=my * Tq, k_off=src * Tq, causal=causal)
        return (k_nxt, v_nxt, m, l, o)

    _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m0, l0, o0))
    l_safe = jnp.maximum(l, 1e-20)
    return o / l_safe.transpose(0, 2, 1)[..., None]


def ring_self_attention(q, k, v, mesh: Mesh, *, axis: str = AXIS_SEQ,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        use_flash: Optional[bool] = None,
                        interpret: bool = False):
    """Sequence-parallel attention: q/k/v [B, T, H, D] with T sharded over
    `axis`. Returns output with the same sharding.

    use_flash: route each shard's block math through the Pallas flash
    kernel (ops/attention.py) instead of the XLA online-softmax sweep.
    Default (None) = auto: on when running on TPU and the local sequence
    block is 128-lane tileable. `interpret=True` runs the kernel in
    interpret mode so the flash path is testable on a CPU mesh."""
    from deeplearning4j_tpu.parallel.mesh import shard_map_compat

    if use_flash is None:
        from deeplearning4j_tpu.ops.attention import flash_eligible

        t_local = q.shape[1] // mesh.shape[axis]
        use_flash = flash_eligible(t_local) and k.shape[1] == q.shape[1]

    spec = P(None, axis, None, None)
    fn = shard_map_compat(
        functools.partial(_ring_attention_local, axis_name=axis,
                          causal=causal, scale=scale, use_flash=use_flash,
                          interpret=interpret),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
