"""NLP / embeddings.

Reference parity: deeplearning4j-nlp-parent (SURVEY §2.5) — SequenceVectors,
Word2Vec, ParagraphVectors, GloVe, vocab construction + Huffman coding,
tokenization pipeline (sentence + document iterators, preprocessor stack),
word-vector serialization.

TPU redesign: the reference trains embeddings with N hogwild threads doing
lock-free scatter updates into shared syn0/syn1 (SURVEY §3.5) — a pattern
with no good TPU analogue. Here each step is ONE jitted computation over a
LARGE batch of (center, context, negatives) indices: embedding gathers →
sampled-softmax loss → autodiff scatter-add gradients (SURVEY §7 hard part
(c): 'redesign as large-batch sharded skipgram'). Data parallelism shards
the pair batch over the mesh like any other model. The generic trainer is
`SequenceVectors` — Word2Vec, ParagraphVectors, and DeepWalk all share it.
"""

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord, build_vocab, HuffmanTree
from deeplearning4j_tpu.nlp.tokenization import (
    AggregatingSentenceIterator, BasicLineIterator,
    CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, FileSentenceIterator,
    LabelAwareListSentenceIterator, LabelAwareSentenceIterator,
    LineSentenceIterator, MultipleEpochsSentenceIterator,
    PrefetchingSentenceIterator, SentenceIterator, StreamLineIterator,
)
from deeplearning4j_tpu.nlp.documents import (
    CollectionDocumentIterator, CollectionLabelAwareIterator,
    CompositePreProcessor, DocumentIterator, FileDocumentIterator,
    FilenamesLabelAwareIterator, FunctionPreProcessor,
    LabelAwareDocumentIterator, LabelAwareIterator, LabelledDocument,
    LabelsSource, LowCasePreProcessor, SentencePreProcessor,
    SimpleLabelAwareIterator, StripSpecialCharsPreProcessor,
)
from deeplearning4j_tpu.nlp.sequence_vectors import (
    AbstractSequenceIterator, CBOW, ElementsLearningAlgorithm,
    LEARNING_ALGORITHMS, Sequence, SequenceElement, SequenceVectors,
    SkipGram,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import (
    write_word_vectors, read_word_vectors, write_binary, read_binary,
)
from deeplearning4j_tpu.nlp.bow import BagOfWordsVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.stopwords import (
    StopWords, StopWordsRemovalPreprocessor,
)

__all__ = [
    "VocabCache", "VocabWord", "build_vocab", "HuffmanTree",
    "DefaultTokenizerFactory", "CommonPreprocessor", "SentenceIterator",
    "CollectionSentenceIterator", "FileSentenceIterator",
    "LineSentenceIterator", "BasicLineIterator", "StreamLineIterator",
    "AggregatingSentenceIterator", "MultipleEpochsSentenceIterator",
    "PrefetchingSentenceIterator", "LabelAwareSentenceIterator",
    "LabelAwareListSentenceIterator",
    "StopWords", "StopWordsRemovalPreprocessor",
    "DocumentIterator", "CollectionDocumentIterator",
    "FileDocumentIterator", "LabelAwareIterator", "LabelledDocument",
    "LabelsSource", "SimpleLabelAwareIterator",
    "CollectionLabelAwareIterator", "FilenamesLabelAwareIterator",
    "LabelAwareDocumentIterator", "SentencePreProcessor",
    "LowCasePreProcessor", "StripSpecialCharsPreProcessor",
    "CompositePreProcessor", "FunctionPreProcessor",
    "SequenceVectors", "SequenceElement", "Sequence",
    "AbstractSequenceIterator", "ElementsLearningAlgorithm", "SkipGram",
    "CBOW", "LEARNING_ALGORITHMS",
    "Word2Vec", "ParagraphVectors", "Glove",
    "write_word_vectors", "read_word_vectors", "write_binary", "read_binary",
    "BagOfWordsVectorizer", "TfidfVectorizer",
]
