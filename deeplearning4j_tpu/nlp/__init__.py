"""NLP / embeddings.

Reference parity: deeplearning4j-nlp-parent (SURVEY §2.5) — SequenceVectors,
Word2Vec, ParagraphVectors, GloVe, vocab construction + Huffman coding,
tokenization pipeline, word-vector serialization.

TPU redesign: the reference trains embeddings with N hogwild threads doing
lock-free scatter updates into shared syn0/syn1 (SURVEY §3.5) — a pattern
with no good TPU analogue. Here each step is ONE jitted computation over a
LARGE batch of (center, context, negatives) indices: embedding gathers →
sampled-softmax loss → autodiff scatter-add gradients (SURVEY §7 hard part
(c): 'redesign as large-batch sharded skipgram'). Data parallelism shards
the pair batch over the mesh like any other model.
"""

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord, build_vocab, HuffmanTree
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, CommonPreprocessor, SentenceIterator,
    CollectionSentenceIterator, FileSentenceIterator, LineSentenceIterator,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import (
    write_word_vectors, read_word_vectors, write_binary, read_binary,
)
from deeplearning4j_tpu.nlp.bow import BagOfWordsVectorizer, TfidfVectorizer

__all__ = [
    "VocabCache", "VocabWord", "build_vocab", "HuffmanTree",
    "DefaultTokenizerFactory", "CommonPreprocessor", "SentenceIterator",
    "CollectionSentenceIterator", "FileSentenceIterator",
    "LineSentenceIterator", "Word2Vec", "ParagraphVectors", "Glove",
    "write_word_vectors", "read_word_vectors", "write_binary", "read_binary",
    "BagOfWordsVectorizer", "TfidfVectorizer",
]
