"""Text annotation pipeline — the UIMA-module analogue.

Reference parity: `deeplearning4j-nlp-uima/` wraps Apache UIMA analysis
engines (ClearTK/OpenNLP wrappers) behind DL4J's tokenizer SPI:
`text/annotator/{SentenceAnnotator,TokenizerAnnotator,PoStagger,
StemmerAnnotator}.java` compose into an AnalysisEngine held by
`text/uima/UimaResource.java`; `PosUimaTokenizer.java` keeps tokens whose
POS is allowed (others become "NONE", optionally stripped) and prefers
lemma/stem over surface; `UimaSentenceIterator.java` yields
pipeline-segmented sentences; `StemmingPreprocessor.java` plugs a
Snowball stemmer into the TokenPreProcess seam.

TPU redesign: UIMA is a Java component framework — its capability here is
the ANNOTATION PIPELINE, so that is what this module provides natively:
a CAS-like `AnnotatedDocument` (text + typed stand-off annotations), an
ordered `AnnotationPipeline` of `Annotator` stages, and concrete
sentence/token/POS/stem annotators (rule-lexicon POS baseline, real
Porter stemmer) that slot into the SAME TokenizerFactory /
TokenPreProcess / SentenceIterator SPIs the rest of nlp/ uses. Treebank
constituency parsing (`text/corpora/treeparser/`) is waived in PARITY.md.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence

from deeplearning4j_tpu.nlp.tokenization import (
    SentenceIterator, TokenPreProcess, Tokenizer, TokenizerFactory,
)

TYPE_SENTENCE = "sentence"
TYPE_TOKEN = "token"


@dataclasses.dataclass
class Annotation:
    """One stand-off annotation (UIMA AnnotationFS analogue): a typed
    [begin, end) span over the document text plus a feature map."""

    type: str
    begin: int
    end: int
    features: Dict[str, object] = dataclasses.field(default_factory=dict)

    def covered_text(self, text: str) -> str:
        return text[self.begin:self.end]


class AnnotatedDocument:
    """CAS analogue: the subject of analysis all annotators share."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []

    def add(self, ann: Annotation) -> Annotation:
        self.annotations.append(ann)
        return ann

    def select(self, type_: str) -> List[Annotation]:
        return sorted((a for a in self.annotations if a.type == type_),
                      key=lambda a: (a.begin, a.end))

    def select_covered(self, type_: str, cover: Annotation) -> List[Annotation]:
        """Annotations of `type_` inside `cover`'s span (JCasUtil
        .selectCovered analogue)."""
        return [a for a in self.select(type_)
                if a.begin >= cover.begin and a.end <= cover.end]


class Annotator:
    """One pipeline stage (UIMA AnalysisEngine analogue)."""

    def process(self, doc: AnnotatedDocument) -> None:
        raise NotImplementedError


class AnnotationPipeline:
    """Ordered annotators over one document (UimaResource analogue:
    `text/uima/UimaResource.java` process/newCas loop)."""

    def __init__(self, *annotators: Annotator):
        self.annotators = list(annotators)

    def process(self, text: str) -> AnnotatedDocument:
        doc = AnnotatedDocument(text)
        for a in self.annotators:
            a.process(doc)
        return doc

    @staticmethod
    def default(pos: bool = True, stem: bool = True) -> "AnnotationPipeline":
        """The UIMA module's stock engine: sentence → token → POS → stem
        (TokenizerAnnotator.getWithAllAnnotators analogue)."""
        stages: List[Annotator] = [SentenceAnnotator(), TokenAnnotator()]
        if pos:
            stages.append(PosAnnotator())
        if stem:
            stages.append(StemmerAnnotator())
        return AnnotationPipeline(*stages)


# ---------------------------------------------------------------- sentences
_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc",
           "e.g", "i.e", "fig", "no", "inc", "ltd", "co", "corp", "u.s",
           "u.k"}

_SENT_END = re.compile(r"[.!?。！？]+[\"'”’)\]]*")


class SentenceAnnotator(Annotator):
    """Rule-based sentence segmentation (reference:
    `text/annotator/SentenceAnnotator.java`, a ClearTK wrapper). Handles
    terminal punctuation incl. CJK, trailing quotes/brackets, and a
    closed abbreviation list."""

    def process(self, doc: AnnotatedDocument) -> None:
        text = doc.text
        start, n = 0, len(text)
        for m in _SENT_END.finditer(text):
            end = m.end()
            word = text[max(start, m.start() - 12):m.start()]
            last = re.split(r"[\s(\[\"']+", word)[-1].lower().rstrip(".")
            if text[m.start()] == "." and (
                    last in _ABBREV
                    or re.fullmatch(r"[a-z]", last)          # initials
                    or (end < n and not text[end:end + 2].strip() == ""
                        and not text[end].isspace())):       # mid-token dot
                continue
            seg = text[start:end].strip()
            if seg:
                b = start + (len(text[start:end])
                             - len(text[start:end].lstrip()))
                doc.add(Annotation(TYPE_SENTENCE, b, end))
            start = end
        tail = text[start:].strip()
        if tail:
            b = start + (len(text[start:]) - len(text[start:].lstrip()))
            doc.add(Annotation(TYPE_SENTENCE, b, b + len(tail)))


# ------------------------------------------------------------------- tokens
_WORD_RE = re.compile(r"\w+|[^\w\s]+", re.UNICODE)


class TokenAnnotator(Annotator):
    """Spans tokens inside each sentence (reference:
    `text/annotator/TokenizerAnnotator.java`). Default: word/punctuation
    regex split with EXACT spans (punctuation becomes its own token, the
    Penn-style behavior the UIMA tokenizer gives); pass any
    TokenizerFactory to tokenize differently."""

    def __init__(self, factory: Optional[TokenizerFactory] = None):
        self.factory = factory

    def process(self, doc: AnnotatedDocument) -> None:
        sentences = doc.select(TYPE_SENTENCE) or [
            Annotation(TYPE_SENTENCE, 0, len(doc.text))]
        # case-insensitive fallback text, computed lazily on the first
        # failed exact find; offsets in it only map back when lowering is
        # length-preserving (e.g. Turkish dotted capital I lowers to two
        # code points) — otherwise the fallback stays disabled
        lowered: Optional[str] = None

        def _lowered() -> str:
            nonlocal lowered
            if lowered is None:
                lowered = doc.text.lower()
                if len(lowered) != len(doc.text):
                    lowered = ""
            return lowered

        for s in sentences:
            if self.factory is None:
                for m in _WORD_RE.finditer(doc.text[s.begin:s.end]):
                    doc.add(Annotation(
                        TYPE_TOKEN, s.begin + m.start(),
                        s.begin + m.end(), {"word": m.group()}))
                continue
            cursor = s.begin
            for tok in self.factory.create(
                    doc.text[s.begin:s.end]).tokens():
                at = doc.text.find(tok, cursor, s.end)
                ltok = tok.lower()
                if at < 0 and len(ltok) == len(tok) and _lowered():
                    # surface changed (e.g. lowercasing preprocessor):
                    # retry case-insensitively so spans still point at
                    # the right characters (only when the token's own
                    # lowering is length-preserving too)
                    at = _lowered().find(ltok, cursor, s.end)
                if at < 0:
                    # the preprocessor rewrote the token beyond recovery
                    # (stemming, n-grams): record a zero-width annotation
                    # at the cursor rather than spanning wrong characters
                    # — covered_text() is then "" instead of garbage
                    doc.add(Annotation(TYPE_TOKEN, cursor, cursor,
                                       {"word": tok}))
                    continue
                doc.add(Annotation(TYPE_TOKEN, at, at + len(tok),
                                   {"word": tok}))
                cursor = at + len(tok)


# --------------------------------------------------------------------- POS
# Closed-class lexicon + suffix rules — the classic deterministic baseline
# tagger (the reference delegates to an OpenNLP maxent model via ClearTK;
# shipping a model binary is out of scope, the seam + tagset match).
_POS_LEXICON: Dict[str, str] = {}
for _w in ("the a an this that these those".split()):
    _POS_LEXICON[_w] = "DT"
for _w in ("i you he she it we they me him her us them".split()):
    _POS_LEXICON[_w] = "PRP"
for _w in ("my your his its our their".split()):
    _POS_LEXICON[_w] = "PRP$"
for _w in ("in on at by for with from of to into over under about "
           "between through during against".split()):
    _POS_LEXICON[_w] = "IN"
for _w in ("and or but nor yet so".split()):
    _POS_LEXICON[_w] = "CC"
for _w in ("is are was were be been being am".split()):
    _POS_LEXICON[_w] = "VBZ" if _w in ("is",) else "VBP"
for _w in ("have has had do does did will would can could shall should "
           "may might must".split()):
    _POS_LEXICON[_w] = "MD" if _w in (
        "will", "would", "can", "could", "shall", "should", "may",
        "might", "must") else "VBP"
for _w in ("not n't never".split()):
    _POS_LEXICON[_w] = "RB"
for _w in ("very quite rather too also just only even still".split()):
    _POS_LEXICON[_w] = "RB"
for _w in ("good great new old big small long little high large quick "
           "brown lazy happy red blue".split()):
    _POS_LEXICON[_w] = "JJ"
for _w in ("run runs ran running jump jumps jumped jumping eat eats ate "
           "eating go goes went going say says said make makes made "
           "see sees saw take takes took".split()):
    _POS_LEXICON[_w] = "VB"


class PosAnnotator(Annotator):
    """Deterministic POS baseline (reference seam:
    `text/annotator/PoStagger.java`). Order: lexicon → shape → suffix →
    default NN; sets the `pos` feature on token annotations."""

    def process(self, doc: AnnotatedDocument) -> None:
        for s in doc.select(TYPE_SENTENCE) or [
                Annotation(TYPE_SENTENCE, 0, len(doc.text))]:
            toks = doc.select_covered(TYPE_TOKEN, s)
            for i, t in enumerate(toks):
                t.features["pos"] = self._tag(
                    t.covered_text(doc.text), first=(i == 0))

    @staticmethod
    def _tag(w: str, first: bool) -> str:
        lw = w.lower()
        if lw in _POS_LEXICON:
            return _POS_LEXICON[lw]
        if re.fullmatch(r"[-+]?\d[\d,.]*", w):
            return "CD"
        if not w[:1].isalpha():
            return "SYM"
        if w[:1].isupper() and not first:
            return "NNP"
        if lw.endswith("ly"):
            return "RB"
        if lw.endswith(("ing",)):
            return "VBG"
        if lw.endswith(("ed",)):
            return "VBD"
        if lw.endswith(("ous", "ful", "ive", "able", "ible", "al", "ic")):
            return "JJ"
        if lw.endswith("s") and not lw.endswith(("ss", "us", "is")):
            return "NNS"
        return "NN"


# ------------------------------------------------------------------ stemmer
class PorterStemmer:
    """The classic Porter (1980) algorithm, steps 1a-5b — the capability
    behind the reference's `StemmerAnnotator.java` (Snowball) and
    `StemmingPreprocessor.java`."""

    _V = "aeiou"

    def _cons(self, w: str, i: int) -> bool:
        c = w[i]
        if c in self._V:
            return False
        if c == "y":
            return i == 0 or not self._cons(w, i - 1)
        return True

    def _m(self, w: str) -> int:
        """Measure: number of VC sequences in `w`."""
        forms = "".join(
            "c" if self._cons(w, i) else "v" for i in range(len(w)))
        return len(re.findall("vc+", forms))

    def _has_vowel(self, w: str) -> bool:
        return any(not self._cons(w, i) for i in range(len(w)))

    def _double_cons(self, w: str) -> bool:
        return (len(w) >= 2 and w[-1] == w[-2] and self._cons(w, len(w) - 1))

    def _cvc(self, w: str) -> bool:
        return (len(w) >= 3 and self._cons(w, len(w) - 3)
                and not self._cons(w, len(w) - 2)
                and self._cons(w, len(w) - 1) and w[-1] not in "wxy")

    def stem(self, word: str) -> str:
        w = word.lower()
        if len(w) <= 2:
            return w
        # step 1a
        for suf, rep in (("sses", "ss"), ("ies", "i"), ("ss", "ss"),
                         ("s", "")):
            if w.endswith(suf):
                w = w[:-len(suf)] + rep
                break
        # step 1b
        if w.endswith("eed"):
            if self._m(w[:-3]) > 0:
                w = w[:-1]
        else:
            hit = None
            for suf in ("ed", "ing"):
                if w.endswith(suf) and self._has_vowel(w[:-len(suf)]):
                    hit = suf
                    break
            if hit:
                w = w[:-len(hit)]
                if w.endswith(("at", "bl", "iz")):
                    w += "e"
                elif self._double_cons(w) and w[-1] not in "lsz":
                    w = w[:-1]
                elif self._m(w) == 1 and self._cvc(w):
                    w += "e"
        # step 1c
        if w.endswith("y") and self._has_vowel(w[:-1]):
            w = w[:-1] + "i"
        # step 2
        for suf, rep in (("ational", "ate"), ("tional", "tion"),
                         ("enci", "ence"), ("anci", "ance"),
                         ("izer", "ize"), ("abli", "able"), ("alli", "al"),
                         ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
                         ("ization", "ize"), ("ation", "ate"),
                         ("ator", "ate"), ("alism", "al"),
                         ("iveness", "ive"), ("fulness", "ful"),
                         ("ousness", "ous"), ("aliti", "al"),
                         ("iviti", "ive"), ("biliti", "ble")):
            if w.endswith(suf):
                if self._m(w[:-len(suf)]) > 0:
                    w = w[:-len(suf)] + rep
                break
        # step 3
        for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                         ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                         ("ness", "")):
            if w.endswith(suf):
                if self._m(w[:-len(suf)]) > 0:
                    w = w[:-len(suf)] + rep
                break
        # step 4
        for suf in ("al", "ance", "ence", "er", "ic", "able", "ible",
                    "ant", "ement", "ment", "ent", "ou", "ism", "ate",
                    "iti", "ous", "ive", "ize"):
            if w.endswith(suf):
                if self._m(w[:-len(suf)]) > 1:
                    w = w[:-len(suf)]
                break
        else:
            if w.endswith("ion") and len(w) > 3 and w[-4] in "st" \
                    and self._m(w[:-3]) > 1:
                w = w[:-3]
        # step 5a
        if w.endswith("e"):
            stem = w[:-1]
            if self._m(stem) > 1 or (self._m(stem) == 1
                                     and not self._cvc(stem)):
                w = stem
        # step 5b
        if self._m(w) > 1 and self._double_cons(w) and w.endswith("l"):
            w = w[:-1]
        return w


class StemmerAnnotator(Annotator):
    """Sets the `stem` feature on tokens (reference:
    `text/annotator/StemmerAnnotator.java`)."""

    def __init__(self, stemmer: Optional[PorterStemmer] = None):
        self.stemmer = stemmer or PorterStemmer()

    def process(self, doc: AnnotatedDocument) -> None:
        for t in doc.select(TYPE_TOKEN):
            word = t.covered_text(doc.text)
            if word.isalpha():
                t.features["stem"] = self.stemmer.stem(word)


class StemmingPreprocessor(TokenPreProcess):
    """TokenPreProcess that stems (reference:
    `tokenizer/preprocessor/StemmingPreprocessor.java` — composes with
    the common preprocessor exactly like the reference subclasses it)."""

    def __init__(self, lowercase: bool = True):
        self.stemmer = PorterStemmer()
        self.lowercase = lowercase

    def pre_process(self, token: str) -> str:
        t = token.lower() if self.lowercase else token
        return self.stemmer.stem(t) if t.isalpha() else t


# ----------------------------------------------- POS-filtered tokenization
class PosFilteredTokenizerFactory(TokenizerFactory):
    """Keep tokens whose POS is allowed; others become "NONE" (or are
    stripped). Prefers stem over surface when available — mirroring
    `PosUimaTokenizer.java:40-75` + `PosUimaTokenizerFactory.java`."""

    def __init__(self, allowed_pos: Iterable[str], *,
                 strip_nones: bool = False, use_stem: bool = True,
                 pipeline: Optional[AnnotationPipeline] = None):
        super().__init__()
        self.allowed = set(allowed_pos)
        self.strip_nones = strip_nones
        self.use_stem = use_stem
        self.pipeline = pipeline or AnnotationPipeline.default()

    def create(self, text: str) -> Tokenizer:
        doc = self.pipeline.process(text)
        out: List[str] = []
        for t in doc.select(TYPE_TOKEN):
            if t.features.get("pos") in self.allowed:
                word = (t.features.get("stem") if self.use_stem else None) \
                    or t.covered_text(doc.text)
                out.append(word)
            elif not self.strip_nones:
                out.append("NONE")
        from deeplearning4j_tpu.nlp.lang import _ListTokenizer

        return _ListTokenizer(out, self._pre)


# ------------------------------------------------------- sentence iterator
class AnnotationSentenceIterator(SentenceIterator):
    """Sentence iterator backed by the pipeline's segmentation
    (reference: `text/sentenceiterator/UimaSentenceIterator.java`)."""

    def __init__(self, documents: Sequence[str],
                 pipeline: Optional[AnnotationPipeline] = None):
        self.documents = list(documents)
        self.pipeline = pipeline or AnnotationPipeline(SentenceAnnotator())

    def __iter__(self):
        for text in self.documents:
            doc = self.pipeline.process(text)
            for a in doc.select(TYPE_SENTENCE):
                yield self._apply_pre(a.covered_text(text))
