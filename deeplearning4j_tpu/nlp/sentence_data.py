"""Sentence → tensor iterators for text classification.

Reference parity: `iterator/CnnSentenceDataSetIterator.java` (SURVEY §2.5)
— tokenizes sentences, looks up word vectors, pads/truncates to a common
length, and emits (features, labels, feature-mask) DataSets for CNN or RNN
sentence classifiers. This is the glue of BASELINE config #3 ("Word2Vec +
LSTM sentiment"): a fitted Word2Vec supplies the lookup; the produced
tensors feed LSTM/CNN stacks directly.

TPU-first notes: fixed `max_length` keeps shapes static across batches (one
XLA compilation); masking carries variable length, matching the recurrent
layers' mask-hold semantics.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)


class WordVectorLookup:
    """Minimal lookup protocol: anything with word_vector(word) -> vec|None
    and a layer_size (Word2Vec, ParagraphVectors, GloVe all qualify)."""

    def __init__(self, model):
        self._m = model
        dim = int(getattr(model, "layer_size", 0) or 0)
        if not dim:
            vocab = getattr(model, "vocab", None)
            if vocab is None or not len(vocab):
                raise ValueError("cannot infer embedding dim from model")
            dim = len(model.word_vector(vocab.word_at(0)))
        self.dim = dim

    def get(self, word: str) -> Optional[np.ndarray]:
        return self._m.word_vector(word)


class SentenceDataSetIterator(DataSetIterator):
    """Labelled sentences → ([B, T, E] features, [B, n_cls] labels,
    [B, T] mask) batches.

    format="rnn" emits [B, T, E] (LSTM input); format="cnn" emits
    [B, T, E, 1]-style NHWC image tensors for 1-D conv sentence models
    (the reference's CNN path)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[int], *,
                 word_vectors, num_classes: Optional[int] = None,
                 batch_size: int = 32, max_length: int = 64,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 fmt: str = "rnn"):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels length mismatch")
        if fmt not in ("rnn", "cnn"):
            raise ValueError(f"unknown format {fmt!r}")
        self.sentences = list(sentences)
        self.labels = list(int(l) for l in labels)
        self.lookup = (word_vectors if isinstance(word_vectors,
                                                  WordVectorLookup)
                       else WordVectorLookup(word_vectors))
        self.num_classes = num_classes or (max(self.labels) + 1)
        bad = [y for y in self.labels if not 0 <= y < self.num_classes]
        if bad:
            raise ValueError(
                f"labels outside [0, {self.num_classes}): {sorted(set(bad))}")
        self._batch = batch_size
        self.max_length = max_length
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.fmt = fmt
        self._pos = 0

    @property
    def batch_size(self):
        return self._batch

    @property
    def num_outcomes(self):
        return self.num_classes

    def reset(self):
        self._pos = 0

    def _encode(self, sentence: str) -> Tuple[np.ndarray, int]:
        toks = self.tf.create(sentence).tokens()
        vecs: List[np.ndarray] = []
        for t in toks:
            v = self.lookup.get(t)
            if v is not None:
                vecs.append(np.asarray(v, np.float32))
            if len(vecs) == self.max_length:
                break
        out = np.zeros((self.max_length, self.lookup.dim), np.float32)
        if vecs:
            out[:len(vecs)] = np.stack(vecs)
        return out, len(vecs)

    def __next__(self) -> DataSet:
        if self._pos >= len(self.sentences):
            raise StopIteration
        lo = self._pos
        hi = min(lo + self._batch, len(self.sentences))
        self._pos = hi
        feats, masks, labs = [], [], []
        for s, y in zip(self.sentences[lo:hi], self.labels[lo:hi]):
            f, n = self._encode(s)
            feats.append(f)
            m = np.zeros((self.max_length,), np.float32)
            m[:max(n, 1)] = 1.0  # at least 1 valid step (all-OOV sentence)
            masks.append(m)
            labs.append(np.eye(self.num_classes, dtype=np.float32)[y])
        x = np.stack(feats)                       # [B, T, E]
        if self.fmt == "cnn":
            x = x[..., None]                      # [B, T, E, 1] NHWC
        return DataSet(x, np.stack(labs), features_mask=np.stack(masks))
