"""Distributed embedding training — dl4j-spark-nlp parity.

Reference parity: `spark/models/embeddings/word2vec/` + `spark/text/
functions/TextPipeline.java` / `CountCumSum.java` (SURVEY §2.4): the
reference tokenizes an RDD, merges per-partition word counts through a
Spark accumulator, broadcasts the vocab, trains word vectors per partition,
and averages the vectors.

TPU-native redesign: the same algorithm without Spark — partitions are
logical workers on the host (or, multi-controller, one partition per
process); counts merge in-process (accumulator ↦ Counter reduction); each
round every worker advances a copy of (syn0, syn1) over its partition with
the SAME batched-XLA steps local Word2Vec uses (hogwild ↦ data-parallel
local SGD, SURVEY §7 hard part (c)), and copies are averaged between
rounds — the ParameterAveragingTrainingMaster scheme applied to embedding
matrices.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _as_token_lists


def merge_partition_counts(counters: Sequence[Counter], min_count: int
                           ) -> VocabCache:
    """Accumulator-equivalent: merge per-partition token counts into one
    vocab (reference: TextPipeline word-count accumulator + CountCumSum)."""
    merged: Counter = Counter()
    for c in counters:
        merged.update(c)
    vocab = VocabCache()
    for word, cnt in sorted(merged.items(), key=lambda kv: (-kv[1], kv[0])):
        if cnt >= min_count:
            vocab.add(VocabWord(word=word, count=int(cnt)))
    return vocab


class DistributedWord2Vec(Word2Vec):
    """Word2Vec over partitioned corpora with per-round vector averaging.

    Same query API as Word2Vec; `fit` distributes. num_workers partitions
    are trained independently each round from the current shared vectors,
    then syn0/syn1 are averaged — exactly the reference Spark scheme
    (per-partition training + vector averaging), with each worker's inner
    loop the batched XLA step rather than hogwild threads."""

    def __init__(self, *, num_workers: int = 4, **kwargs):
        super().__init__(**kwargs)
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers

    def fit(self, corpus) -> "DistributedWord2Vec":
        import jax

        sentences = _as_token_lists(corpus, self.tokenizer_factory)
        parts: List[List] = [sentences[i::self.num_workers]
                             for i in range(self.num_workers)]
        parts = [p for p in parts if p]
        # Phase 1: per-partition counts → accumulator merge → global vocab.
        self.vocab = merge_partition_counts(
            [Counter(w for s in part for w in s) for part in parts],
            self.min_count)
        if len(self.vocab) == 0:
            raise ValueError("Empty vocabulary (min_count too high?)")

        rng = np.random.default_rng(self.seed)
        setup = self._setup(rng)
        params = setup["params"]
        part_idx = [self._index_sentences(p) for p in parts]
        total_est = sum(len(s) for pi in part_idx for s in pi) \
            * self.window * max(self.epochs, 1)
        seen = 0
        avg = jax.tree_util.tree_map
        # Phase 2: rounds of per-partition training + vector averaging.
        for epoch in range(self.epochs):
            results = []
            advanced = 0
            for w, pi in enumerate(part_idx):
                wrng = np.random.default_rng(
                    self.seed + 1009 * (epoch + 1) + w)
                p_w, seen_w = self._run_epoch(
                    params, pi, setup, wrng, seen, total_est)
                results.append(p_w)
                advanced += seen_w - seen
            # All workers' pairs count toward the global LR decay — total_est
            # sums across partitions, so `seen` must too, or the linear decay
            # would stall at ~1/num_workers of its schedule.
            seen += advanced
            n = len(results)
            params = avg(lambda *xs: sum(xs) / n, *results)
        self.syn0 = np.asarray(params["syn0"])
        self._syn1 = np.asarray(params["syn1"])
        return self
