"""Document iterators, label sources, and the sentence-preprocessor stack.

Reference parity: `text/documentiterator/` (11 impls — DocumentIterator,
FileDocumentIterator, LabelAwareIterator, LabelledDocument, LabelsSource,
SimpleLabelAwareIterator, FilenamesLabelAwareIterator, ...) and
`text/sentenceiterator/SentencePreProcessor` + the preprocessor
implementations the sentence/document iterators compose.

These feed ParagraphVectors/Word2Vec exactly as in the reference: a
document iterator yields `LabelledDocument`s whose content is tokenized by
the model's TokenizerFactory; `LabelsSource` generates/stores the document
labels that become doc-vector keys.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable, Iterable, Iterator, List, Optional, Sequence


# ----------------------------------------------------- preprocessor stack
class SentencePreProcessor:
    """Reference: `sentenceiterator/SentencePreProcessor` SPI."""

    def pre_process(self, sentence: str) -> str:
        return sentence


class LowCasePreProcessor(SentencePreProcessor):
    """Reference: prefetch/LowCasePreProcessor."""

    def pre_process(self, sentence: str) -> str:
        return sentence.lower()


class StripSpecialCharsPreProcessor(SentencePreProcessor):
    """Strip everything but word chars and whitespace (reference:
    StringCleaning.stripPunct used by the default pipelines)."""

    _RE = re.compile(r"[^\w\s]")

    def pre_process(self, sentence: str) -> str:
        return self._RE.sub("", sentence)


class CompositePreProcessor(SentencePreProcessor):
    """Apply a chain of preprocessors in order (reference: the
    preprocessor stacking done by TextPipeline)."""

    def __init__(self, *pres: SentencePreProcessor):
        self.pres = list(pres)

    def pre_process(self, sentence: str) -> str:
        for p in self.pres:
            sentence = p.pre_process(sentence)
        return sentence


class FunctionPreProcessor(SentencePreProcessor):
    """Wrap any str→str callable as a preprocessor."""

    def __init__(self, fn: Callable[[str], str]):
        self.fn = fn

    def pre_process(self, sentence: str) -> str:
        return self.fn(sentence)


# ------------------------------------------------------------- documents
@dataclasses.dataclass
class LabelledDocument:
    """Reference: `documentiterator/LabelledDocument` (content + labels)."""

    content: str
    labels: List[str] = dataclasses.field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelsSource:
    """Reference: `documentiterator/LabelsSource` — generates sequential
    labels (template with %d) and/or records every label seen."""

    def __init__(self, template: str = "DOC_%d",
                 labels: Optional[Sequence[str]] = None):
        self.template = template
        self._labels: List[str] = list(labels) if labels else []
        self._counter = 0

    def next_label(self) -> str:
        label = self.template % self._counter
        self._counter += 1
        self._labels.append(label)
        return label

    def store_label(self, label: str) -> None:
        if label not in self._labels:
            self._labels.append(label)

    @property
    def labels(self) -> List[str]:
        return list(self._labels)

    def reset(self) -> None:
        self._counter = 0
        self._labels = []


class DocumentIterator:
    """Reference: `documentiterator/DocumentIterator` SPI — a stream of
    documents (whole texts, vs sentence iterators' single sentences)."""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionDocumentIterator(DocumentIterator):
    def __init__(self, docs: Sequence[str],
                 pre: Optional[SentencePreProcessor] = None):
        self._docs = list(docs)
        self._pre = pre

    def __iter__(self):
        for d in self._docs:
            yield self._pre.pre_process(d) if self._pre else d


class FileDocumentIterator(DocumentIterator):
    """One document per FILE under a path (the reference's
    FileDocumentIterator contract; FileSentenceIterator is per-line)."""

    def __init__(self, path: str,
                 pre: Optional[SentencePreProcessor] = None):
        self.path = path
        self._pre = pre

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        return sorted(
            os.path.join(d, f)
            for d, _, fs in os.walk(self.path) for f in fs)

    def __iter__(self):
        for fp in self._files():
            with open(fp, "r", errors="replace") as f:
                text = f.read()
            yield self._pre.pre_process(text) if self._pre else text


# ---------------------------------------------------- label-aware layer
class LabelAwareIterator:
    """Reference: `documentiterator/LabelAwareIterator` SPI — yields
    LabelledDocuments and exposes the LabelsSource."""

    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    @property
    def labels_source(self) -> LabelsSource:
        raise NotImplementedError

    def reset(self):
        pass


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wrap any iterable of LabelledDocuments (reference:
    SimpleLabelAwareIterator)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)
        self._source = LabelsSource()
        for d in self._docs:
            for l in d.labels:
                self._source.store_label(l)

    def __iter__(self):
        return iter(self._docs)

    @property
    def labels_source(self) -> LabelsSource:
        return self._source


class CollectionLabelAwareIterator(SimpleLabelAwareIterator):
    """Texts + auto-generated (or provided) labels."""

    def __init__(self, docs: Sequence[str],
                 labels: Optional[Sequence[str]] = None,
                 template: str = "DOC_%d"):
        src = LabelsSource(template)
        out = []
        for i, text in enumerate(docs):
            label = labels[i] if labels is not None else src.next_label()
            out.append(LabelledDocument(content=text, labels=[label]))
        super().__init__(out)
        if labels is None:
            self._source = src

    @property
    def labels_source(self) -> LabelsSource:
        return self._source


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """One document per file, labelled by its filename (reference:
    FilenamesLabelAwareIterator)."""

    def __init__(self, path: str, *, absolute_labels: bool = False):
        self._inner = FileDocumentIterator(path)
        self.absolute_labels = absolute_labels
        self._source = LabelsSource()

    def __iter__(self):
        # single directory walk: label and content come from the SAME file
        # listing (a concurrent file add/remove can't misalign them)
        for fp in self._inner._files():
            with open(fp, "r", errors="replace") as f:
                text = f.read()
            label = fp if self.absolute_labels else os.path.basename(fp)
            self._source.store_label(label)
            yield LabelledDocument(content=text, labels=[label])

    @property
    def labels_source(self) -> LabelsSource:
        return self._source


class LabelAwareDocumentIterator(LabelAwareIterator):
    """Adapter: plain DocumentIterator + generated labels →
    LabelAwareIterator (reference: DocumentIteratorConverter)."""

    def __init__(self, documents: DocumentIterator,
                 template: str = "DOC_%d"):
        self._docs = documents
        self._source = LabelsSource(template)

    def __iter__(self):
        # deterministic labels across passes: each iteration restarts the
        # generator, so pass 2 re-yields D0, D1, ... for the same documents
        self._source.reset()
        for text in self._docs:
            yield LabelledDocument(content=text,
                                   labels=[self._source.next_label()])

    @property
    def labels_source(self) -> LabelsSource:
        return self._source

    def reset(self):
        self._docs.reset()
        self._source.reset()
