"""Word-vector serialization — text + Google binary word2vec formats.

Reference parity: `models/embeddings/loader/WordVectorSerializer.java`
(2,829 LoC): writeWordVectors/loadTxtVectors (text: "word v1 v2 ...") and
the Google word2vec binary format (header "V D\\n", then per word: name,
space, D float32 little-endian). Both formats interop with the reference
and with original word2vec/gensim tooling.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def write_word_vectors(model, path: str) -> None:
    """Text format. Reference: WordVectorSerializer.writeWordVectors."""
    with open(path, "w", encoding="utf-8") as f:
        for i in range(len(model.vocab)):
            vec = " ".join(f"{v:.6f}" for v in model.syn0[i])
            f.write(f"{model.vocab.word_at(i)} {vec}\n")


def read_word_vectors(path: str) -> Tuple[VocabCache, np.ndarray]:
    """Reference: WordVectorSerializer.loadTxtVectors."""
    words, rows = [], []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append(np.array([float(x) for x in parts[1:]], np.float32))
    vocab = VocabCache()
    for w in words:
        vocab.add(VocabWord(word=w, count=1))
    return vocab, np.stack(rows)


def write_binary(model, path: str) -> None:
    """Google word2vec binary format. Reference:
    WordVectorSerializer.writeWordVectors(binary=true)."""
    V, D = model.syn0.shape
    with open(path, "wb") as f:
        f.write(f"{V} {D}\n".encode())
        for i in range(V):
            f.write(model.vocab.word_at(i).encode("utf-8") + b" ")
            f.write(model.syn0[i].astype("<f4").tobytes())
            f.write(b"\n")


def read_binary(path: str) -> Tuple[VocabCache, np.ndarray]:
    """Reference: WordVectorSerializer.loadGoogleModel(binary=true).

    Hot path: the body is parsed by the native C++ codec
    (`native.w2v_parse` — one scan, bulk vector memcpy, the host-side
    equivalent of the reference's buffered-stream loader for GB-scale
    files); byte-by-byte Python remains as the no-toolchain fallback."""
    with open(path, "rb") as f:
        header = f.readline().decode().strip().split()
        V, D = int(header[0]), int(header[1])
        body_start = f.tell()
        from deeplearning4j_tpu import native

        parsed = native.w2v_parse(f.read(), V, D) if native.available() \
            else None
        if parsed is not None:
            words, mat = parsed
            vocab = VocabCache()
            for w in words:
                vocab.add(VocabWord(word=w, count=1))
            return vocab, mat
        f.seek(body_start)
        vocab = VocabCache()
        mat = np.zeros((V, D), np.float32)
        for i in range(V):
            word = bytearray()
            while True:
                ch = f.read(1)
                if ch in (b" ", b""):
                    break
                if ch not in (b"\n", b"\r"):   # CRLF files: match native
                    word.extend(ch)
            mat[i] = np.frombuffer(f.read(4 * D), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                f.seek(-1, 1)
            vocab.add(VocabWord(word=word.decode("utf-8"), count=1))
    return vocab, mat
